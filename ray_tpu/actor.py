"""Actors: stateful workers with ordered method execution.

Reference surfaces: python/ray/actor.py (ActorClass/ActorHandle/
ActorMethod), src/ray/core_worker/transport/actor_task_submitter (per-
actor ordered queues, seq numbers), src/ray/gcs/gcs_server/
gcs_actor_manager.cc (lifecycle FSM: PENDING_CREATION → ALIVE →
[RESTARTING →] DEAD).

Semantics kept:
  - creation is scheduled like a task (resources honored); method calls
    go DIRECTLY to the actor's ordered inbox, bypassing the scheduler —
    the reference's actor-task fast path.
  - per-caller FIFO ordering (single inbox thread); max_concurrency > 1
    relaxes ordering like threaded actors; async def methods run on an
    asyncio loop (async actors).
  - method exceptions do NOT kill the actor; __init__ failure marks the
    actor DEAD; ray_tpu.kill() → ActorDiedError for pending calls;
    max_restarts recreates state via lineage (re-running __init__).
  - default resource behavior: actors take 1 CPU for *creation* then hold
    0 while alive, unless resources were explicitly requested, in which
    case they are held for the actor's lifetime.
"""

from __future__ import annotations

import asyncio
import enum
import functools
import inspect
import queue
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import exceptions as rex
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import ActorID, ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.task_spec import TaskSpec, TaskType, resources_to_vector
from ray_tpu._private import trace_plane
from ray_tpu.remote_function import _DEFAULT_OPTIONS, _build_resources

def _effective_max_restarts(opts: dict) -> int:
    """Per-actor option wins; unset (None) falls back to the
    ``actor_max_restarts`` knob."""
    mr = opts.get("max_restarts")
    if mr is None:
        from ray_tpu._private.config import GLOBAL_CONFIG
        mr = GLOBAL_CONFIG.actor_max_restarts
    return int(mr)


_ACTOR_OPTIONS = dict(_DEFAULT_OPTIONS)
_ACTOR_OPTIONS.update(dict(
    max_restarts=None,  # None = GLOBAL_CONFIG.actor_max_restarts
    max_task_retries=0,
    max_concurrency=1,
    max_pending_calls=-1,
    lifetime=None,  # None | "detached"
    namespace="default",
    # named thread pools with independent queues (reference:
    # concurrency_groups={"io": 2}); methods route with
    # @ray_tpu.method(concurrency_group="io")
    concurrency_groups=None,
))


class ActorState(enum.Enum):
    DEPENDENCIES_UNREADY = 0
    PENDING_CREATION = 1
    ALIVE = 2
    RESTARTING = 3
    DEAD = 4


class _Call:
    __slots__ = ("method_name", "args", "kwargs", "return_ids", "num_returns",
                 "task_id", "trace_ctx", "dedup")

    def __init__(self, method_name, args, kwargs, return_ids, num_returns,
                 task_id, trace_ctx=None, dedup=False):
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs
        self.return_ids = return_ids
        self.num_returns = num_returns
        self.task_id = task_id
        self.trace_ctx = trace_ctx
        # p2p head-fallback retries carry preset ids and dedup=True:
        # the worker's completion cache makes the re-run exactly-once
        self.dedup = dedup


class _ActorRuntime:
    """Host-side actor executor: ordered inbox + worker thread(s)."""

    def __init__(self, worker, actor_id: ActorID, cls, init_args, init_kwargs,
                 opts: Dict[str, Any], creation_spec: TaskSpec,
                 creation_node_index: int):
        self.worker = worker
        self.actor_id = actor_id
        self.cls = cls
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.opts = opts
        self.state = ActorState.PENDING_CREATION
        self.instance = None
        self.inbox: "queue.Queue[Optional[_Call]]" = queue.Queue()
        # named concurrency groups: each group gets its OWN queue and
        # thread pool — a saturated "compute" group never blocks "io"
        # methods (reference: core_worker concurrency groups)
        groups = dict(opts.get("concurrency_groups") or {})
        self._group_inboxes: Dict[str, "queue.Queue[Optional[_Call]]"] = {
            g: queue.Queue() for g in groups}
        self._group_sizes: Dict[str, int] = {
            g: max(1, int(n)) for g, n in groups.items()}
        # method -> target inbox (or the unknown-group ValueError),
        # resolved ONCE: submit() sits on the actor-call hot path and
        # routing is static at class+options time
        self._route: Dict[str, Any] = {}
        for mname, m in inspect.getmembers(cls, callable):
            g = getattr(m, "__ray_tpu_concurrency_group__", None)
            if g is None:
                continue
            target = self._group_inboxes.get(g)
            self._route[mname] = target if target is not None else \
                ValueError(
                    f"method {mname!r} routes to unknown concurrency "
                    f"group {g!r}; declared: {sorted(groups)}")
        self.init_done = threading.Event()
        self.death_cause: Optional[BaseException] = None
        self.num_restarts = 0
        self.num_executed = 0
        self.name: Optional[str] = opts.get("name")
        self.namespace: str = opts.get("namespace") or "default"
        self.detached = opts.get("lifetime") == "detached"
        self._creation_spec = creation_spec
        self._creation_node_index = creation_node_index
        # the row currently charged for the actor's lifetime resources;
        # restart-elsewhere moves the charge (and release at death)
        self._current_node_index = creation_node_index
        self._explicit_resources = bool(
            opts.get("resources") or opts.get("num_tpus")
            or (opts.get("num_cpus") not in (None, 1.0, 1)))
        self._is_async = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(cls, inspect.isfunction))
        self._concurrency = max(1, int(opts.get("max_concurrency", 1)))
        self._threads: List[threading.Thread] = []
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stopped = threading.Event()
        # True when re-attached to an already-initialized worker after a
        # head restart: the inbox loop starts, __init__ does NOT re-run
        self._adopted = False

    # -- runtime_env (thread-mode actors share the driver process: env
    # vars save/restore around init and each call, same documented
    # caveat as thread-mode tasks; process actors apply them for their
    # dedicated process's lifetime) --------------------------------------
    def _env_apply(self):
        env_vars = (self._creation_spec.runtime_env or {}).get("env_vars")
        if not env_vars:
            return None
        from ray_tpu._private.worker import env_vars_push

        env_vars_push(env_vars)
        return env_vars

    @staticmethod
    def _env_restore(env_vars) -> None:
        if env_vars is None:
            return
        from ray_tpu._private.worker import env_vars_pop

        env_vars_pop(env_vars)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._is_async:
            t = threading.Thread(target=self._async_main, daemon=True,
                                 name=f"actor-{self.actor_id.hex()[:8]}")
            t.start()
            self._threads = [t]
        else:
            for i in range(self._concurrency):
                t = threading.Thread(target=self._sync_main, args=(i,),
                                     daemon=True,
                                     name=f"actor-{self.actor_id.hex()[:8]}-{i}")
                t.start()
                self._threads.append(t)
            for group, n in self._group_sizes.items():
                inbox = self._group_inboxes[group]
                for i in range(n):
                    t = threading.Thread(
                        target=self._group_main, args=(inbox,),
                        daemon=True,
                        name=(f"actor-{self.actor_id.hex()[:8]}"
                              f"-{group}-{i}"))
                    t.start()
                    self._threads.append(t)

    def _run_init(self) -> bool:
        env_saved = self._env_apply()
        try:
            self.instance = self.cls(*self.init_args, **self.init_kwargs)
            self.state = ActorState.ALIVE
            self.worker.memory_store.put(
                _creation_object_id(self.actor_id), "ALIVE")
            return True
        except BaseException as e:  # noqa: BLE001
            tb = "".join(traceback.format_exception(type(e), e, e.__traceback__))
            err = rex.TaskError(f"{self.cls.__name__}.__init__", e, tb)
            self.death_cause = err
            self.state = ActorState.DEAD
            self.worker.memory_store.put(
                _creation_object_id(self.actor_id), err, is_exception=True)
            return False
        finally:
            self._env_restore(env_saved)
            self.init_done.set()
            # default actors release their creation CPU once alive
            if not self._explicit_resources:
                self.worker.scheduler.notify_task_finished(
                    self._creation_spec.task_id, self._current_node_index,
                    self._creation_spec.resources)

    def _sync_main(self, thread_index: int):
        if thread_index == 0:
            if self._adopted:
                self.init_done.set()  # worker already holds the instance
            else:
                ok = self._run_init()
                if not ok:
                    self._drain_with_error()
                    return
        else:
            self.init_done.wait()
            if self.state == ActorState.DEAD:
                return
        while not self._stopped.is_set():
            call = self.inbox.get()
            if call is None:
                break
            self._execute_call(call)

    def _group_main(self, inbox: "queue.Queue[Optional[_Call]]"):
        self.init_done.wait()
        if self.state == ActorState.DEAD:
            return
        while not self._stopped.is_set():
            call = inbox.get()
            if call is None:
                break
            self._execute_call(call)

    def _async_main(self):
        ok = self._run_init()
        if not ok:
            self._drain_with_error()
            return
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        # async actors default to high concurrency (reference: 1000)
        limit = (self._concurrency if self.opts.get("max_concurrency", 1) > 1
                 else 1000)
        sem = asyncio.Semaphore(limit)

        async def run_one(call):
            async with sem:
                await self._execute_call_async(call)

        def pump():
            # daemon thread: blocking inbox reads posted into the loop
            while True:
                call = self.inbox.get()
                if call is None:
                    loop.call_soon_threadsafe(loop.stop)
                    return
                loop.call_soon_threadsafe(
                    lambda c=call: loop.create_task(run_one(c)))

        pump_thread = threading.Thread(
            target=pump, daemon=True,
            name=f"actor-pump-{self.actor_id.hex()[:8]}")
        pump_thread.start()
        try:
            loop.run_forever()
        finally:
            for p in asyncio.all_tasks(loop):
                p.cancel()
            loop.close()

    # -- execution ---------------------------------------------------------
    def _capture_pg_token(self):
        """Actors created with placement_group_capture_child_tasks=True
        propagate their group to tasks submitted from method bodies
        (mirrors Worker._execute_task for normal tasks)."""
        spec = self._creation_spec
        if spec.placement_group_id is not None \
                and spec.placement_group_capture_child_tasks:
            from ray_tpu.util.placement_group import _current_pg
            return _current_pg.set(spec.placement_group_id)
        return None

    def _reset_pg_token(self, token) -> None:
        if token is not None:
            from ray_tpu.util.placement_group import _current_pg
            _current_pg.reset(token)

    def _trace_done(self, call: _Call, timing, offset: float = 0.0,
                    worker_key=None) -> None:
        tp = getattr(self.worker, "trace_plane", None)
        if tp is None or call.trace_ctx is None:
            return
        tp.record_finished_batch(
            ((call.task_id, timing,
              worker_key if worker_key is not None
              else threading.get_ident(),
              self._current_node_index),), offset=offset)

    def _trace_failed(self, call: _Call, exc: BaseException) -> None:
        tp = getattr(self.worker, "trace_plane", None)
        if tp is None or call.trace_ctx is None:
            return
        tp.record_failed(call.task_id, type(exc).__name__)

    def _execute_call(self, call: _Call):
        method = getattr(self.instance, call.method_name)
        pg_token = self._capture_pg_token()
        env_saved = self._env_apply()
        try:
            args, kwargs, dep_err = self._resolve(call.args, call.kwargs)
            if dep_err is not None:
                raise dep_err
            t0 = time.time()
            with trace_plane.parent_scope(call.trace_ctx):
                result = method(*args, **kwargs)
            if inspect.isgenerator(result):
                result = list(result)
            t1 = time.time()
            self._store(call, result)
            self._trace_done(call, (t0, t1))
        except BaseException as e:  # noqa: BLE001
            self._store_error(call, e)
            self._trace_failed(call, e)
        finally:
            self._env_restore(env_saved)
            self._reset_pg_token(pg_token)
            self.num_executed += 1

    async def _execute_call_async(self, call: _Call):
        method = getattr(self.instance, call.method_name)
        pg_token = self._capture_pg_token()
        env_saved = self._env_apply()
        try:
            args, kwargs, dep_err = self._resolve(call.args, call.kwargs)
            if dep_err is not None:
                raise dep_err
            t0 = time.time()
            with trace_plane.parent_scope(call.trace_ctx):
                result = method(*args, **kwargs)
                if inspect.iscoroutine(result):
                    result = await result
            t1 = time.time()
            self._store(call, result)
            self._trace_done(call, (t0, t1))
        except BaseException as e:  # noqa: BLE001
            self._store_error(call, e)
            self._trace_failed(call, e)
        finally:
            self._env_restore(env_saved)
            self._reset_pg_token(pg_token)
            self.num_executed += 1

    def _resolve(self, args, kwargs):
        dep_err = None

        def r(v):
            nonlocal dep_err
            if isinstance(v, ObjectRef):
                entry = self.worker.memory_store.get_entry(v.object_id())
                if entry is None:
                    # actor calls resolve deps by blocking get (direct path)
                    try:
                        return self.worker.get([v], timeout=None)[0]
                    except BaseException as e:  # noqa: BLE001
                        dep_err = e
                        return None
                if entry.is_exception:
                    dep_err = entry.value
                    return None
                return entry.value
            return v

        return (tuple(r(a) for a in args),
                {k: r(v) for k, v in kwargs.items()}, dep_err)

    def _store(self, call: _Call, result):
        if call.num_returns == 1:
            values = [result]
        else:
            values = list(result)
        for oid, v in zip(call.return_ids, values):
            self.worker.memory_store.put(oid, v)
            self.worker.scheduler.notify_object_ready(oid)

    def _store_error(self, call: _Call, exc: BaseException):
        if not isinstance(exc, (rex.TaskError, rex.ActorError)):
            tb = "".join(traceback.format_exception(type(exc), exc,
                                                    exc.__traceback__))
            exc = rex.TaskError(f"{self.cls.__name__}.{call.method_name}",
                                exc, tb)
        for oid in call.return_ids:
            self.worker.memory_store.put(oid, exc, is_exception=True)
            self.worker.scheduler.notify_object_ready(oid)

    def _drain_with_error(self):
        err = self.death_cause or rex.ActorDiedError(actor_id=self.actor_id)
        for inbox in (self.inbox, *self._group_inboxes.values()):
            while True:
                try:
                    call = inbox.get_nowait()
                except queue.Empty:
                    break
                if call is not None:
                    self._store_error(call, err)

    # -- submission (from handles) ----------------------------------------
    def submit(self, call: _Call):
        if self.state == ActorState.DEAD:
            self._store_error(call, self.death_cause
                              or rex.ActorDiedError(actor_id=self.actor_id))
            return
        inbox = self.inbox
        if self._route:
            target = self._route.get(call.method_name)
            if isinstance(target, ValueError):
                # the tag promises isolation: an undeclared group must
                # fail loudly even when NO groups were declared (a
                # silently serialized "io" method is exactly the bug
                # the tag exists to prevent)
                self._store_error(call, target)
                return
            if target is not None:
                inbox = target
        limit = self.opts.get("max_pending_calls", -1)
        if limit > 0 and inbox.qsize() >= limit:
            raise rex.PendingCallsLimitExceeded(
                f"actor has {inbox.qsize()} pending calls (limit {limit})")
        inbox.put(call)

    # -- death / restart ---------------------------------------------------
    def stop(self, no_restart: bool = True,
             cause: Optional[BaseException] = None):
        max_restarts = _effective_max_restarts(self.opts)
        can_restart = (not no_restart
                       and (max_restarts == -1
                            or self.num_restarts < max_restarts))
        if can_restart:
            self.num_restarts += 1
            self.state = ActorState.RESTARTING
            # restart = re-run __init__ (lineage-style state reconstruction)
            try:
                self.instance = self.cls(*self.init_args, **self.init_kwargs)
                self.state = ActorState.ALIVE
                return
            except BaseException as e:  # noqa: BLE001
                self.death_cause = rex.TaskError(
                    f"{self.cls.__name__}.__init__ (restart)", e)
        self.state = ActorState.DEAD
        self.death_cause = self.death_cause or cause or rex.ActorDiedError(
            "actor killed via ray_tpu.kill()", actor_id=self.actor_id)
        self._stopped.set()
        for _ in self._threads:
            self.inbox.put(None)
        for g, n in self._group_sizes.items():
            for _ in range(n):
                self._group_inboxes[g].put(None)
        self._drain_with_error()
        # lifetime-held resources released at death
        if self._explicit_resources:
            self.worker.scheduler.notify_task_finished(
                self._creation_spec.task_id, self._current_node_index,
                self._creation_spec.resources)
        with self.worker._actors_lock:
            self.worker.actors.pop(self.actor_id, None)
            self.worker.dead_actors.add(self.actor_id)
        self.worker.gcs.update_actor_state(self.actor_id, "DEAD")


class _ProcessActorRuntime(_ActorRuntime):
    """Actor whose instance lives in a DEDICATED worker process
    (reference: every actor is its own worker process; the GCS actor
    scheduler leases one at creation — src/ray/gcs/gcs_server/
    gcs_actor_scheduler.cc). The driver keeps the FSM + ordered inbox;
    __init__ and method calls ship over the worker's pipe; large
    arguments/results move through the shm arena. Worker-process death
    is detected by the pool monitor and drives restart (max_restarts)
    or DEAD — real crash detection, not only explicit kill()."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._pool = (self.worker.pool_for_node(self._creation_node_index)
                      or self.worker.process_pool)
        self._h = None
        self._round_done = threading.Event()
        self._round_result = None
        self._restart_lock = threading.Lock()

    def _select_pool(self):
        """Pool to (re)spawn the actor worker on.

        Same node while it lives (resources stay charged there). On node
        death: a placement-grouped actor follows its (rescheduled) bundle
        rows; a plain actor moves to an alive node that can ACCEPT its
        resource charge (scheduler.try_allocate) so the replacement node
        is never overcommitted. Returns None when nothing qualifies."""
        w = self.worker
        spec = self._creation_spec
        if self._pool is not None and not self._pool._node_dead:
            return self._pool
        if spec.placement_group_id is not None:
            entry = w.placement_groups.get(spec.placement_group_id)
            if entry is None or entry.state != "CREATED":
                return None
            bindex = spec.placement_group_bundle_index
            rows = entry.rows if bindex < 0 else (
                [entry.rows[bindex]] if bindex < len(entry.rows) else [])
            for r in rows:
                ns = w.scheduler.node_state(r)
                if ns is None or ns.defunct:
                    continue
                pool = w.pool_for_node(r)
                if pool is None or pool._node_dead:
                    continue
                if not self._explicit_resources \
                        or w.scheduler.try_allocate(r, spec.resources):
                    self._current_node_index = r
                    return pool
            return None
        for e in w.gcs.alive_process_nodes():
            if e.pool is None or e.pool._node_dead:
                continue
            if not self._explicit_resources \
                    or w.scheduler.try_allocate(e.index, spec.resources):
                self._current_node_index = e.index
                return e.pool
        return None

    def start(self):
        self._h = self._pool.spawn_actor_worker(self)
        super().start()

    # -- pool reader/monitor callbacks -------------------------------------
    def _on_worker_ready(self, h):
        pass  # readiness observed by polling h.ready in _create_remote

    def _on_remote_done(self, task_id, entries, timing=None):
        self._round_result = ("done", entries, timing)
        self._round_done.set()

    def _on_remote_err(self, task_id, blob, tb):
        self._round_result = ("err", blob, tb)
        self._round_done.set()

    def _on_process_died(self, h, cause):
        if h is not self._h:
            return  # an already-replaced worker
        self._round_result = ("died", cause)
        self._round_done.set()
        # crash detection: restart (or die) off the monitor thread
        threading.Thread(
            target=self.stop,
            kwargs=dict(no_restart=False,
                        cause=rex.ActorDiedError(
                            f"actor worker process died: {cause}",
                            actor_id=self.actor_id)),
            daemon=True).start()

    # -- remote rounds ------------------------------------------------------
    def _remote_round(self, kind: str, payload: dict):
        self._round_done.clear()
        self._round_result = None
        h = self._h
        try:
            self._pool.send_to(h, (kind, payload))
        except (OSError, ValueError, AttributeError) as e:
            return ("died", e)
        # poll the handle while waiting: kill() releases the worker
        # without a monitor notification, and the event-set in stop()
        # can race a concurrent clear
        while not self._round_done.wait(timeout=0.25):
            if h.dead and not self._round_done.is_set():
                return ("died", "worker released")
        return self._round_result

    def _build_payload(self, h, task_id, return_ids, args, kwargs,
                       extra: dict):
        import cloudpickle

        from ray_tpu._private.runtime.process_pool import _dumps_collect_refs

        # actor calls BLOCK on not-yet-ready args (the direct-path
        # semantics of the base runtime's _resolve), unlike normal tasks
        # whose readiness the scheduler guarantees
        for v in list(args) + list(kwargs.values()):
            if isinstance(v, ObjectRef) and \
                    self.worker.memory_store.get_entry(v.object_id()) is None:
                self.worker.memory_store.wait_and_get([v.object_id()], None)
        sargs = tuple(self._pool._resolve_for_ship(a) for a in args)
        skw = {k: self._pool._resolve_for_ship(v) for k, v in kwargs.items()}
        args_blob, contained = _dumps_collect_refs((sargs, skw))
        payload = dict(
            task_id=task_id.binary(),
            name=f"{self.cls.__name__}",
            args_blob=args_blob,
            num_returns=max(1, len(return_ids)),
            return_ids=[o.binary() for o in return_ids],
        )
        payload.update(extra)
        # borrows are keyed by the worker registered AT BUILD TIME — a
        # restart swaps self._h, and removal must target the original
        borrows = []
        for r in contained:
            self.worker.reference_counter.add_borrower(
                r.object_id(), h.worker_id)
            borrows.append((r.object_id(), h.worker_id))
        return payload, borrows

    def _remove_borrows(self, h, borrows) -> None:
        for oid, wid in borrows:
            self.worker.reference_counter.remove_borrower(oid, wid)
        # puts issued from inside the actor during this round (tracked on
        # the handle by _rpc_put) are released the same way normal-task
        # workers release them in _release()
        if h is not None:
            for oid in h.borrows:
                self.worker.reference_counter.remove_borrower(
                    oid, h.worker_id)
            h.borrows = set()

    def _create_remote(self):
        """Returns True on success or the causing exception."""
        import cloudpickle
        import time as _time

        deadline = _time.monotonic() + 60
        while self._h is None or not self._h.ready:
            if _time.monotonic() > deadline:
                return TimeoutError("actor worker never registered")
            _time.sleep(0.005)
        creation_oid = _creation_object_id(self.actor_id)
        h = self._h
        # actor_bin lets the node daemon record WHICH actor this
        # dedicated worker hosts (head-restart re-adoption)
        extra = dict(cls_blob=cloudpickle.dumps(self.cls),
                     actor_bin=self.actor_id.binary())
        renv = self._creation_spec.runtime_env or {}
        if renv.get("working_dir_pkg"):
            # the actor OWNS its worker process: the env applies for
            # its whole lifetime (reference: per-actor runtime_env)
            extra["actor_working_dir_pkg"] = renv["working_dir_pkg"]
        if renv.get("pip"):
            extra["actor_pip"] = list(renv["pip"])
        env_vars = (self._creation_spec.runtime_env or {}).get("env_vars")
        if env_vars:
            # the actor OWNS its worker process: env_vars apply for its
            # whole lifetime (reference: per-actor runtime_env).
            # "actor_env_vars", NOT "env_vars": the generic task key is
            # save/restored per payload, which would undo them after
            # __init__
            extra["actor_env_vars"] = dict(env_vars)
        try:
            payload, borrows = self._build_payload(
                h, self._creation_spec.task_id, [creation_oid],
                self.init_args, self.init_kwargs, extra)
        except Exception as e:
            return e
        res = self._remote_round("actor_create", payload)
        self._remove_borrows(h, borrows)
        if res[0] == "done":
            return True
        if res[0] == "err":
            try:
                return cloudpickle.loads(res[1])
            except Exception:
                return RuntimeError("actor __init__ failed (undecodable)")
        return rex.ActorDiedError(
            f"worker died during __init__: {res[1]}",
            actor_id=self.actor_id)

    def _run_init(self) -> bool:
        try:
            res = self._create_remote()
            if res is True:
                self.state = ActorState.ALIVE
                self.worker.memory_store.put(
                    _creation_object_id(self.actor_id), "ALIVE")
                return True
            exc = res if isinstance(res, BaseException) else RuntimeError(res)
            if not isinstance(exc, rex.TaskError):
                exc = rex.TaskError(f"{self.cls.__name__}.__init__", exc, "")
            self.death_cause = exc
            self.state = ActorState.DEAD
            self.worker.memory_store.put(
                _creation_object_id(self.actor_id), exc, is_exception=True)
            # don't leak the dedicated worker of a failed creation
            h, self._h = self._h, None
            if h is not None:
                self._pool.release_actor_worker(h, kill=True)
            return False
        finally:
            self.init_done.set()
            if not self._explicit_resources:
                self.worker.scheduler.notify_task_finished(
                    self._creation_spec.task_id, self._current_node_index,
                    self._creation_spec.resources)

    def _execute_call(self, call: _Call):
        import cloudpickle
        import time as _time

        max_task_retries = int(self.opts.get("max_task_retries", 0))
        attempt = 0
        failed_h = None
        while True:
            # a restart may be in flight; calls queue until it settles.
            # After a died round, ALSO wait for the handle to actually
            # change: the failing send can observe the old handle before
            # stop() swaps it, and instant retries would burn every
            # attempt against the same dead worker.
            deadline = _time.monotonic() + 60
            while (self.state == ActorState.RESTARTING or self._h is None
                   or self._h is failed_h) \
                    and self.state != ActorState.DEAD \
                    and _time.monotonic() < deadline:
                _time.sleep(0.005)
            if self.state == ActorState.DEAD:
                self._store_error(call, self.death_cause
                                  or rex.ActorDiedError(
                                      actor_id=self.actor_id))
                return
            h = self._h
            if h is None:
                self._store_error(call, rex.ActorUnavailableError(
                    f"actor worker unavailable for {call.method_name}"))
                return
            extra = dict(method=call.method_name)
            if call.dedup:
                extra["dedup"] = True
            if call.trace_ctx is not None and call.trace_ctx[3]:
                # same payload-dict carriage as normal task leases
                extra["trace"] = call.trace_ctx
            try:
                payload, borrows = self._build_payload(
                    h, call.task_id, call.return_ids, call.args, call.kwargs,
                    extra)
            except Exception as e:
                self._store_error(call, e)
                return
            res = self._remote_round("actor_call", payload)
            if res[0] == "done":
                self._pool.store_result_entries(call.return_ids, res[1])
                self._trace_done(call,
                                 res[2] if len(res) > 2 else None,
                                 offset=self._pool.clock_offset,
                                 worker_key=h.worker_id.hex())
            elif res[0] == "err":
                try:
                    exc = cloudpickle.loads(res[1])
                except Exception:
                    exc = RuntimeError("actor call failed (undecodable)")
                self._store_error(call, exc)
                self._trace_failed(call, exc)
            elif attempt < max_task_retries:
                # worker died mid-call (restart driven by
                # _on_process_died): retry on the restarted instance
                # (reference: max_task_retries re-runs actor tasks after
                # restart, ray: python/ray/actor.py)
                attempt += 1
                failed_h = h
                self._remove_borrows(h, borrows)
                continue
            else:
                self._store_error(call, rex.ActorDiedError(
                    f"actor worker died during {call.method_name}: "
                    f"{res[1]}", actor_id=self.actor_id))
            # results registered first, THEN borrows dropped (a returned
            # ref gets its driver-side local ref before the borrow goes
            # away)
            self._remove_borrows(h, borrows)
            self.num_executed += 1
            return

    def stop(self, no_restart: bool = True,
             cause: Optional[BaseException] = None):
        with self._restart_lock:
            if self.state == ActorState.DEAD:
                return
            max_restarts = _effective_max_restarts(self.opts)
            can_restart = (not no_restart
                           and (max_restarts == -1
                                or self.num_restarts < max_restarts))
            h, self._h = self._h, None
            if h is not None:
                self._pool.release_actor_worker(h, kill=True)
                # an in-flight call is blocked in _remote_round; the
                # monitor won't notify (we marked the handle released),
                # so unblock it here or its return refs never resolve
                if not self._round_done.is_set():
                    self._round_result = ("died", cause or "killed")
                    self._round_done.set()
            if can_restart:
                pool = self._select_pool()
                if pool is None:
                    cause = cause or rex.ActorDiedError(
                        "no alive node to restart the actor on",
                        actor_id=self.actor_id)
                else:
                    self.num_restarts += 1
                    self.state = ActorState.RESTARTING
                    self._pool = pool
                    self._h = pool.spawn_actor_worker(self)
                    res = self._create_remote()
                    if res is True:
                        self.state = ActorState.ALIVE
                        self.worker.gcs.update_actor_state(
                            self.actor_id, "ALIVE", pool.node_index)
                        return
                    self.death_cause = (
                        res if isinstance(res, BaseException)
                        else rex.TaskError(
                            f"{self.cls.__name__}.__init__ (restart)",
                            res, ""))
            self.state = ActorState.DEAD
            self.death_cause = self.death_cause or cause \
                or rex.ActorDiedError("actor killed via ray_tpu.kill()",
                                      actor_id=self.actor_id)
            self._stopped.set()
            for _ in self._threads:
                self.inbox.put(None)
            for g, n in self._group_sizes.items():
                for _ in range(n):
                    self._group_inboxes[g].put(None)
            self._drain_with_error()
            if self._explicit_resources:
                self.worker.scheduler.notify_task_finished(
                    self._creation_spec.task_id, self._current_node_index,
                    self._creation_spec.resources)
            with self.worker._actors_lock:
                self.worker.actors.pop(self.actor_id, None)
                self.worker.dead_actors.add(self.actor_id)
            self.worker.gcs.update_actor_state(self.actor_id, "DEAD")


def _creation_object_id(actor_id: ActorID) -> ObjectID:
    return ObjectID.for_task_return(TaskID.for_actor_task(actor_id, 0), 0)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, *, num_returns: Optional[int] = None,
                **unknown) -> "ActorMethod":
        if unknown:
            raise TypeError(
                f"ActorMethod.options() got unsupported options "
                f"{sorted(unknown)} (supported: num_returns)")
        return ActorMethod(
            self._handle, self._method_name,
            self._num_returns if num_returns is None else num_returns)

    def bind(self, *args, **kwargs):
        """DAG-building (reference: ray.dag actor-method nodes)."""
        from ray_tpu.dag import ActorMethodNode

        return ActorMethodNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(self._method_name, args, kwargs,
                                           self._num_returns)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name!r} must be invoked with "
            f".remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = ""):
        self._actor_id = actor_id
        self._class_name = class_name
        self._seq = 0
        self._seq_lock = threading.Lock()
        # per-handle task-id namespace salt. RANDOM, not id(self): a
        # handle in a RESTARTED head (or one allocated at a recycled
        # address) must not reuse an old handle's task ids — the old
        # results may still sit in a surviving node arena, and a
        # colliding create would reject the new result
        import os as _os
        self._salt = int.from_bytes(_os.urandom(2), "big")

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def _runtime(self) -> _ActorRuntime:
        import time as _time

        worker = worker_mod.get_worker()
        deadline = _time.monotonic() + 60.0
        while True:
            with worker._actors_lock:
                rt = worker.actors.get(self._actor_id)
                dead = self._actor_id in worker.dead_actors
            if rt is not None:
                return rt
            if dead or _time.monotonic() > deadline:
                raise rex.ActorDiedError(
                    f"actor {self._actor_id.hex()} does not exist or is dead",
                    actor_id=self._actor_id)
            # creation may still be queued behind deps/resources
            _time.sleep(0.001)

    def _submit_method(self, method_name, args, kwargs, num_returns):
        worker = worker_mod.get_worker()
        if getattr(worker, "is_client", False):
            return worker.actor_call(self._actor_id, method_name, args,
                                     kwargs, num_returns)
        if not hasattr(worker, "actors"):
            # inside a process worker: the actor runtime tables live
            # with the owner — route the call over the pipe RPC
            return worker.actor_call(self._actor_id, method_name, args,
                                     kwargs, num_returns)
        rt = self._runtime()
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        task_id = TaskID.for_actor_task(self._actor_id,
                                        self._salt * 65536 + seq)
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(num_returns)]
        for oid in return_ids:
            worker.reference_counter.add_owned_object(oid)
        call = _Call(method_name, args, kwargs, return_ids, num_returns,
                     task_id)
        tp = getattr(worker, "trace_plane", None)
        if tp is not None:
            # child of the ambient parent: a driver call roots a new
            # trace, a call from inside a traced task/client op joins it
            call.trace_ctx = tp.make_context()
            tp.on_actor_call(call,
                             f"{self._class_name}.{method_name}",
                             rt._current_node_index)
        rt.submit(call)
        refs = [ObjectRef(oid, worker.worker_id) for oid in return_ids]
        return refs[0] if num_returns == 1 else refs

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id.binary(), self._class_name))

    def __repr__(self):
        return (f"ActorHandle({self._class_name}, "
                f"{self._actor_id.hex()[:16]})")


def _rebuild_handle(actor_binary: bytes, class_name: str) -> ActorHandle:
    return ActorHandle(ActorID(actor_binary), class_name)


class ActorClass:
    def __init__(self, cls: type, options: Dict[str, Any]):
        self._cls = cls
        self._options = dict(_ACTOR_OPTIONS)
        if "num_gpus" in options:
            options["num_tpus"] = options.pop("num_gpus")
        # actor default: no CPU option given -> 1 CPU for creation only
        self._options.update(options)
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated "
            f"directly; use {self._cls.__name__}.remote()")

    def options(self, **overrides) -> "ActorClass":
        if "num_gpus" in overrides:
            overrides["num_tpus"] = overrides.pop("num_gpus")
        for k in overrides:
            if k not in _ACTOR_OPTIONS and k != "name":
                raise ValueError(f"unknown actor option {k!r}")
        merged = dict(self._options)
        merged.update(overrides)
        new = ActorClass.__new__(ActorClass)
        new._cls = self._cls
        new._options = merged
        return new

    def _validate_concurrency_groups(self) -> None:
        """Fail at CALL time, not deep in actor bootstrap (a bootstrap
        raise would leave the creation object pending forever)."""
        groups = self._options.get("concurrency_groups")
        if not groups:
            return
        if any(inspect.iscoroutinefunction(m) for _, m in
               inspect.getmembers(self._cls, inspect.isfunction)):
            # async actors run one event loop; group-tagged calls would
            # land in queues no loop drains
            raise ValueError(
                "concurrency_groups are not supported on ASYNC actors: "
                "async methods already interleave on one event loop "
                "(use max_concurrency to bound them)")
        from ray_tpu._private.config import GLOBAL_CONFIG

        if GLOBAL_CONFIG.worker_mode == "process":
            # process-actor rounds share one reply slot; concurrent
            # group threads would cross-wire results
            raise ValueError(
                "concurrency_groups require thread-mode actors; "
                "process-mode actors execute calls through a single "
                "ordered round-trip")

    def remote(self, *args, **kwargs) -> ActorHandle:
        self._validate_concurrency_groups()
        worker = worker_mod.get_worker()
        if getattr(worker, "is_client", False):
            return worker.create_actor(self._cls, self._options, args,
                                       kwargs)
        opts = self._options
        name = opts.get("name")
        namespace = opts.get("namespace") or "default"
        if name and worker.gcs.get_actor_by_name(name, namespace) is not None:
            raise ValueError(
                f"actor name {name!r} already taken in namespace "
                f"{namespace!r}")

        actor_id = ActorID.of(worker.job_id)
        creation_task_id = TaskID.for_actor_task(actor_id, 0)
        creation_oid = _creation_object_id(actor_id)
        worker.reference_counter.add_owned_object(creation_oid)
        worker.reference_counter.pin(creation_oid)

        spec = TaskSpec(
            task_id=creation_task_id,
            name=f"{self._cls.__name__}.__init__",
            func=None,
            func_descriptor=f"{self._cls.__module__}.{self._cls.__name__}",
            args=args,
            kwargs=kwargs,
            num_returns=1,
            resources=_build_resources(opts),
            task_type=TaskType.ACTOR_CREATION_TASK,
            actor_id=actor_id,
            scheduling_strategy=opts.get("scheduling_strategy"),
            placement_group_id=None,
            runtime_env=opts.get("runtime_env"),
        )
        from ray_tpu.remote_function import _validate_runtime_env
        _validate_runtime_env(spec.runtime_env)
        renv = spec.runtime_env or {}
        if renv.get("working_dir") or renv.get("pip"):
            # working_dir/pip apply for the DEDICATED worker process's
            # lifetime; thread-mode actors share the driver process and
            # cannot isolate them — fail eagerly when no process-backed
            # node could ever host this actor
            if not worker.needs_serialized_funcs:
                raise NotImplementedError(
                    "actor runtime_env working_dir/pip need a process-"
                    "backed node (worker_mode='process' or a cluster "
                    "node); this cluster is thread-only")
            spec.runtime_env = worker.prepare_runtime_env(renv)
        pg = opts.get("placement_group")
        strategy = opts.get("scheduling_strategy")
        if strategy is not None and hasattr(strategy, "placement_group"):
            pg = strategy.placement_group
            spec.placement_group_bundle_index = getattr(
                strategy, "placement_group_bundle_index", -1)
            spec.placement_group_capture_child_tasks = getattr(
                strategy, "placement_group_capture_child_tasks", False)
        if pg is not None:
            spec.placement_group_id = pg.id if hasattr(pg, "id") else pg
            from ray_tpu.remote_function import _validate_bundle_fit
            _validate_bundle_fit(worker, spec.placement_group_id,
                                 spec.placement_group_bundle_index,
                                 spec.resources)

        cls, copts = self._cls, dict(opts)
        is_async = any(inspect.iscoroutinefunction(m) for _, m in
                       inspect.getmembers(cls, inspect.isfunction))
        # actor registry: the GCS actor table is the source of truth
        # (reference: GcsActorManager). DETACHED actors additionally
        # journal a recovery payload: they are meant to outlive their
        # owner, so a restarted head can re-attach them to their still-
        # running worker process (the reference keeps the serialized
        # creation spec in the actor table for the same reason).
        recovery = None
        if copts.get("lifetime") == "detached":
            import cloudpickle
            try:
                # init args ride along: a re-adopted actor that later
                # crashes restarts through the normal max_restarts path,
                # which re-runs __init__ with these
                recovery = cloudpickle.dumps((cls, copts, args, kwargs))
            except Exception:
                recovery = None  # unpicklable class: no head-restart FT
        worker.gcs.register_actor(actor_id, name or "", namespace,
                                  self._cls.__name__, worker.job_id,
                                  recovery=recovery)

        def create(pending, node_index, _worker=worker):
            # process mode: sync single-threaded actors get a dedicated
            # worker process on the ASSIGNED node (reference behavior);
            # async/threaded actors stay host-side (their event loop /
            # thread pool lives with the driver until process-side loops
            # land)
            rt_cls = _ActorRuntime
            if (_worker.pool_for_node(node_index) is not None and not is_async
                    and int(copts.get("max_concurrency", 1)) == 1):
                rt_cls = _ProcessActorRuntime
            rt = rt_cls(_worker, actor_id, cls, args, kwargs, copts,
                        spec, node_index)
            with _worker._actors_lock:
                _worker.actors[actor_id] = rt
            rt.start()
            _worker.gcs.update_actor_state(actor_id, "ALIVE", node_index)

        from ray_tpu._private.scheduler.base import PendingTask
        deps = [a.object_id() for a in args if isinstance(a, ObjectRef)]
        deps += [v.object_id() for v in kwargs.values()
                 if isinstance(v, ObjectRef)]
        unresolved = [d for d in deps if not worker.memory_store.contains(d)]
        pending = PendingTask(spec=spec, deps=unresolved, execute=create)
        # route through the scheduler so creation respects resources
        _submit_actor_creation(worker, pending, create)
        handle = ActorHandle(actor_id, self._cls.__name__)
        return handle


def adopt_process_actor(worker, actor_id: ActorID, entry, recovery: bytes,
                        pool, h, node_index: int):
    """Re-attach a journaled detached actor to its STILL-RUNNING worker
    process after a head restart (see Worker.readopt_remote_node). The
    worker holds the live instance; only the head-side runtime (inbox,
    ordered execution, borrow bookkeeping) is rebuilt."""
    import cloudpickle

    from ray_tpu._private.task_spec import TaskSpec, TaskType

    blob = cloudpickle.loads(recovery)
    cls, opts, init_args, init_kwargs = (blob if len(blob) == 4
                                         else (*blob, (), {}))
    spec = TaskSpec(
        task_id=TaskID.for_actor_task(actor_id, 0),
        name=f"{cls.__name__}.__init__",
        func=None,
        func_descriptor=f"{cls.__module__}.{cls.__name__}.__init__",
        args=tuple(init_args),
        kwargs=dict(init_kwargs),
        num_returns=1,
        resources=_build_resources(opts),
        task_type=TaskType.ACTOR_CREATION_TASK,
        actor_id=actor_id,
    )
    rt = _ProcessActorRuntime(worker, actor_id, cls, tuple(init_args),
                              dict(init_kwargs), dict(opts),
                              spec, node_index)
    rt._pool = pool
    rt._h = h
    h.actor_rt = rt
    with pool._lock:
        pool._actor_handles.append(h)
    # lifetime resources re-charge on the rejoined node (best effort:
    # the fresh scheduler row has full capacity)
    if rt._explicit_resources:
        worker.scheduler.try_allocate(node_index, spec.resources)
    rt._adopted = True
    rt.state = ActorState.ALIVE
    rt.init_done.set()
    worker.memory_store.put(_creation_object_id(actor_id), "ALIVE")
    with worker._actors_lock:
        worker.actors[actor_id] = rt
    _ActorRuntime.start(rt)  # inbox loop only; no worker spawn/re-init
    worker.gcs.update_actor_state(actor_id, "ALIVE", node_index)
    return rt


def _submit_actor_creation(worker, pending, create):
    """Actor creation dispatches via the scheduler (so it respects
    resources/placement) but executes the _ActorRuntime bootstrap instead of
    a plain function call; the worker dispatcher recognizes _actor_boot."""
    pending.spec._actor_boot = create  # type: ignore[attr-defined]
    worker.scheduler.submit(pending)


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    worker = worker_mod.get_worker()
    if getattr(worker, "is_client", False):
        return worker.get_actor(name, namespace)
    actor_id = worker.gcs.get_actor_by_name(name, namespace)
    if actor_id is None:
        raise ValueError(f"no actor named {name!r} in namespace "
                         f"{namespace!r}")
    with worker._actors_lock:
        rt = worker.actors.get(actor_id)
    if rt is None:
        raise ValueError(f"actor {name!r} is registered but not running "
                         "(still being created, or dead)")
    return ActorHandle(actor_id, rt.cls.__name__)


def kill(handle: ActorHandle, *, no_restart: bool = True) -> None:
    worker = worker_mod.get_worker()
    if getattr(worker, "is_client", False):
        worker.kill_actor(handle.actor_id, no_restart)
        return
    with worker._actors_lock:
        rt = worker.actors.get(handle.actor_id)
    if rt is None:
        return
    rt.init_done.wait(timeout=30)
    rt.stop(no_restart=no_restart)
