"""@remote function decorator and submission options.

Reference surface: python/ray/remote_function.py (RemoteFunction,
._remote(), .options()) — same semantics: free functions become task
factories; `.remote(*args)` returns ObjectRef(s); `.options()` overrides
resources/retries/strategy per call site.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Dict, Optional

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.task_spec import TaskSpec, TaskType

_DEFAULT_OPTIONS = dict(
    num_cpus=1.0,
    num_tpus=0.0,
    memory=0.0,
    resources=None,
    num_returns=1,
    max_retries=None,
    retry_exceptions=False,
    timeout_s=None,
    name=None,
    scheduling_strategy=None,
    placement_group=None,
    placement_group_bundle_index=-1,
    runtime_env=None,
    # QoS plane (config.qos): strict priority tier (higher wins; may
    # preempt) and owning tenant for weighted fair-share. Inert when
    # the plane is off.
    priority=0,
    tenant=None,
)


def _build_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    res = {"CPU": float(opts["num_cpus"])}
    if opts["num_tpus"]:
        res["TPU"] = float(opts["num_tpus"])
    if opts["memory"]:
        res["memory"] = float(opts["memory"])
    if opts["resources"]:
        res.update(opts["resources"])
    return res


class RemoteFunction:
    def __init__(self, func: Callable, options: Optional[Dict[str, Any]] = None):
        self._function = func
        self._name = getattr(func, "__qualname__", getattr(func, "__name__", "fn"))
        self._module = getattr(func, "__module__", "")
        self._options = dict(_DEFAULT_OPTIONS)
        if options:
            self._options.update(options)
        self._is_generator = inspect.isgeneratorfunction(func)
        # function blob pickled ONCE per RemoteFunction, like the
        # reference's pickled_function export (ray:
        # python/ray/remote_function.py) — per-call cloudpickle of the
        # same function was the single largest task-submission cost.
        # NOTE closure values are captured at first .remote(), matching
        # the reference's freeze-at-export semantics.
        self._fn_blob: Optional[bytes] = None
        self._fn_id: Optional[bytes] = None
        self._exec_func: Optional[Callable] = None
        # default-placement scheduling class, computed once per
        # RemoteFunction: scheduling_class() on the admission hot path
        # re-sorted the resources dict per task otherwise
        res = _build_resources(self._options)
        strat = self._options["scheduling_strategy"]
        place = ("spread",) if strat == "SPREAD" else ("default",)
        self._class_key = (
            (f"{self._module}.{self._name}",
             tuple(sorted(res.items())), place)
            if (self._options["placement_group"] is None
                and (strat is None or isinstance(strat, str)))
            else None)
        # submission fast path: everything per-call-invariant is decided
        # here once, so `.remote()` with plain options is a TaskSpec
        # construction + submit and nothing else. The resources dict is
        # SHARED across this function's specs (read-only downstream).
        self._resources = res
        self._descriptor = f"{self._module}.{self._name}"
        self._fast = (strat is None
                      and self._options["placement_group"] is None
                      and not self._options["runtime_env"]
                      and not self._is_generator
                      and isinstance(self._options["num_returns"], int))
        functools.update_wrapper(self, func)

    def bind(self, *args, **kwargs):
        """DAG-building (reference: ray.dag): returns a node; compose
        with other .bind() results and experimental_compile()."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._name} cannot be called directly; use "
            f"{self._name}.remote() or access the original via "
            f"{self._name}.func")

    @property
    def func(self) -> Callable:
        """The undecorated function (upstream: .__wrapped__ / _function)."""
        return self._function

    def options(self, **overrides) -> "RemoteFunction":
        for k in overrides:
            if k not in _DEFAULT_OPTIONS and k != "num_gpus":
                raise ValueError(f"unknown option {k!r}")
        if "num_gpus" in overrides:  # portability alias
            overrides["num_tpus"] = overrides.pop("num_gpus")
        merged = dict(self._options)
        merged.update(overrides)
        return RemoteFunction(self._function, merged)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def _fast_setup(self, worker, opts):
        """Shared per-CALL setup of the fast submission lane (used by
        both _remote's fast path and map_remote): resolved exec func,
        cached serialized blob, effective max_retries."""
        func = self._exec_func
        if func is None:
            func = self._exec_func = self._function
        if self._fn_blob is None and worker.needs_serialized_funcs:
            import hashlib

            import cloudpickle
            self._fn_blob = cloudpickle.dumps(func)
            self._fn_id = hashlib.sha1(self._fn_blob).digest()
        max_retries = opts["max_retries"]
        if max_retries is None:
            from ray_tpu._private.config import GLOBAL_CONFIG
            max_retries = GLOBAL_CONFIG.task_max_retries
        return func, max_retries

    def map_remote(self, args_list) -> list:
        """Vectorized submission: one task per args tuple, returning a
        ref per task (num_returns==1 shape). Equivalent to
        ``[f.remote(*a) for a in args_list]`` with the per-task
        submit bookkeeping amortized into per-batch lock holds and a
        single scheduler wakeup — the task-path analog of the
        scheduler's batched lease grants. Falls back to the one-at-a-
        time path for options the fast path doesn't cover (placement
        groups, runtime envs, generators, num_returns != 1)."""
        worker = worker_mod.get_worker()
        opts = self._options
        fast = (self._fast and opts["num_returns"] == 1
                and not self._is_generator
                and getattr(worker, "submit_task_batch", None) is not None)
        if fast:
            from ray_tpu.util.placement_group import _current_pg
            fast = _current_pg.get() is None
        if not fast:
            return [self._remote(tuple(a), {}, opts) for a in args_list]
        func, max_retries = self._fast_setup(worker, opts)
        name = opts["name"] or self._name
        retry_exceptions = opts["retry_exceptions"]
        next_task_id = worker.next_task_id
        specs = [TaskSpec(
            task_id=next_task_id(),
            name=name,
            func=func,
            func_descriptor=self._descriptor,
            args=tuple(a),
            kwargs={},
            num_returns=1,
            resources=self._resources,
            max_retries=max_retries,
            retry_exceptions=retry_exceptions,
            timeout_s=opts["timeout_s"],
            serialized_func=self._fn_blob,
            func_id=self._fn_id,
            class_key=self._class_key,
            priority=opts["priority"],
            tenant=opts["tenant"] or "default",
        ) for a in args_list]
        return [refs[0] for refs in worker.submit_task_batch(specs)]

    def _remote(self, args, kwargs, opts):
        worker = worker_mod.get_worker()
        if opts is self._options and self._fast:
            from ray_tpu.util.placement_group import _current_pg
            if _current_pg.get() is None:
                func, max_retries = self._fast_setup(worker, opts)
                num_returns = opts["num_returns"]
                spec = TaskSpec(
                    task_id=worker.next_task_id(),
                    name=opts["name"] or self._name,
                    func=func,
                    func_descriptor=self._descriptor,
                    args=args,
                    kwargs=kwargs,
                    num_returns=num_returns,
                    resources=self._resources,
                    max_retries=max_retries,
                    retry_exceptions=opts["retry_exceptions"],
                    timeout_s=opts["timeout_s"],
                    serialized_func=self._fn_blob,
                    func_id=self._fn_id,
                    class_key=self._class_key,
                    priority=opts["priority"],
                    tenant=opts["tenant"] or "default",
                )
                refs = worker.submit_task(spec)
                return refs[0] if num_returns == 1 else refs
        num_returns = opts["num_returns"]
        generator = self._is_generator or num_returns in ("dynamic", "streaming")
        if generator and isinstance(num_returns, str):
            num_returns = 1
        from ray_tpu._private.config import GLOBAL_CONFIG
        max_retries = opts["max_retries"]
        if max_retries is None:
            max_retries = GLOBAL_CONFIG.task_max_retries

        pg = opts["placement_group"]
        pg_id = None
        bundle_index = opts["placement_group_bundle_index"]
        strategy = opts["scheduling_strategy"]
        capture = False
        if strategy is not None and hasattr(strategy, "placement_group"):
            pg = strategy.placement_group
            bundle_index = getattr(strategy, "placement_group_bundle_index", -1)
            capture = getattr(strategy,
                              "placement_group_capture_child_tasks", False)
        if pg is None and strategy is None:
            # inside a capture_child_tasks task: children inherit the group
            from ray_tpu.util.placement_group import _current_pg
            inherited = _current_pg.get()
            if inherited is not None:
                pg, capture = inherited, True
        if pg is not None:
            pg_id = pg.id if hasattr(pg, "id") else pg
            _validate_bundle_fit(worker, pg_id, bundle_index,
                                 _build_resources(opts))
        _validate_runtime_env(opts["runtime_env"])

        func = self._exec_func
        if func is None:
            func = self._function
            if generator:
                func = _collect_generator(func)
            self._exec_func = func
        if self._fn_blob is None and worker.needs_serialized_funcs:
            import hashlib

            import cloudpickle
            self._fn_blob = cloudpickle.dumps(func)
            self._fn_id = hashlib.sha1(self._fn_blob).digest()

        spec = TaskSpec(
            task_id=worker.next_task_id(),
            name=opts["name"] or self._name,
            func=func,
            func_descriptor=f"{self._module}.{self._name}",
            args=args,
            kwargs=kwargs,
            num_returns=num_returns,
            resources=_build_resources(opts),
            max_retries=max_retries,
            retry_exceptions=opts["retry_exceptions"],
            timeout_s=opts["timeout_s"],
            task_type=TaskType.NORMAL_TASK,
            scheduling_strategy=strategy,
            placement_group_id=pg_id,
            placement_group_bundle_index=bundle_index,
            placement_group_capture_child_tasks=capture,
            runtime_env=opts["runtime_env"],
            serialized_func=self._fn_blob,
            func_id=self._fn_id,
            generator=generator,
            # the precomputed key only describes the no-group case; an
            # inherited/explicit placement group changes the class
            class_key=self._class_key if pg_id is None else None,
            priority=opts["priority"],
            tenant=opts["tenant"] or "default",
        )
        refs = worker.submit_task(spec)
        return refs[0] if spec.num_returns == 1 else refs


def _validate_runtime_env(runtime_env) -> None:
    """Supported: env_vars (both worker modes), working_dir (zipped,
    content-addressed per-node cache), pip (venv per spec; LOCAL
    wheel/dir requirements only — this environment has no network
    egress). Reference: the per-node runtime env agent,
    ray: python/ray/_private/runtime_env/. Unsupported keys raise
    instead of being silently dropped."""
    if not runtime_env:
        return
    supported = {"env_vars", "working_dir", "pip", "working_dir_pkg"}
    extra = set(runtime_env) - supported
    if extra:
        raise NotImplementedError(
            f"runtime_env keys {sorted(extra)} are not supported "
            f"(supported: {sorted(supported - {'working_dir_pkg'})})")
    env_vars = runtime_env.get("env_vars") or {}
    if not isinstance(env_vars, dict) or not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env_vars.items()):
        raise TypeError("runtime_env['env_vars'] must be a "
                        "str -> str dict")
    wd = runtime_env.get("working_dir")
    if wd is not None and not isinstance(wd, str):
        raise TypeError("runtime_env['working_dir'] must be a path str")
    pip = runtime_env.get("pip")
    if pip is not None and not (isinstance(pip, list) and all(
            isinstance(p, str) for p in pip)):
        raise TypeError("runtime_env['pip'] must be a list of "
                        "requirement strings (local paths here)")


def _validate_bundle_fit(worker, pg_id, bundle_index, resources) -> None:
    """Reject tasks whose demand can never fit their target bundle(s) —
    otherwise they would wait forever (reference raises the same way,
    ray: python/ray/util/placement_group.py check_placement_group_index +
    resource validation)."""
    manager = getattr(worker, "placement_groups", None)
    if manager is None:
        return  # worker-process shim: the owner validates at admission
    entry = manager.get(pg_id)
    if entry is None:
        return
    if entry.state in ("REMOVED", "INFEASIBLE"):
        raise ValueError(
            f"placement group {pg_id.hex()[:16]} is {entry.state} and "
            "cannot accept tasks")
    import numpy as np

    from ray_tpu._private.task_spec import resources_to_vector

    demand = np.asarray(resources_to_vector(resources), dtype=np.float32)
    bundles = entry.demands
    if bundle_index >= 0:
        if bundle_index >= len(bundles):
            raise ValueError(
                f"bundle index {bundle_index} out of range: placement "
                f"group has {len(bundles)} bundles")
        ok = bool((bundles[bundle_index] >= demand).all())
    else:
        ok = bool((bundles >= demand[None, :]).all(axis=1).any())
    if not ok:
        raise ValueError(
            f"task demand {resources} cannot fit "
            f"{'bundle %d' % bundle_index if bundle_index >= 0 else 'any bundle'}"
            f" of placement group {pg_id.hex()[:16]} "
            f"(bundles: {entry.bundles})")


def _collect_generator(func):
    @functools.wraps(func)
    def wrapper(*a, **k):
        return list(func(*a, **k))
    return wrapper


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=2)`` for functions and classes."""
    from ray_tpu.actor import ActorClass

    def decorate(obj, options=None):
        if inspect.isclass(obj):
            return ActorClass(obj, options or {})
        if not callable(obj):
            raise TypeError("@remote requires a function or class")
        return RemoteFunction(obj, options)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. "
                        "@remote(num_cpus=2)")
    if "num_gpus" in kwargs:
        kwargs["num_tpus"] = kwargs.pop("num_gpus")
    return lambda obj: decorate(obj, kwargs)
