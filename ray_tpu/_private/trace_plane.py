"""Cluster-wide causal tracing plane.

The task event plane (task_events.py) records per-attempt lifecycles
and the log plane captures output, but neither links records causally.
This module adds the missing layer: a ``TraceContext`` — a plain
4-tuple ``(trace_id, span_id, parent_span_id, sampled)`` — stamped
into :class:`TaskSpec` at submit, carried to workers inside the
existing wire envelopes (a ``"trace"`` key in the task payload dict
and a sixth element in the actor-call blob; no new framed tags), and
restored worker-side so nested ``.remote()`` submissions and actor
calls inherit parentage automatically.  The logical span survives
retries because retry mutates the spec in place: each attempt becomes
its own record under the same ``span_id`` (attempt spans are derived
as ``span#attempt`` at export time, children of the logical span).

Propagation is ambient: whoever is about to run user code installs the
code's own context with :func:`parent_scope`, and submission paths ask
:func:`current_parent` — a thread-local, so the driver's thread-mode
execution, the head's per-request client threads, and the head-side
RPC handlers for worker-nested submissions all compose without passing
contexts through call signatures.

The :class:`TraceAggregator` mirrors ``TaskEventAggregator``
structurally: plain-list records with fixed indices, one lock, batch
hooks that hold it once, worker-side ``(t0, t1)`` windows mapped onto
the head's clock axis via the same per-pool ``clock_offset``, and
bounded retention — here keyed by trace_id, evicting the least
recently active trace wholesale when ``traces_max`` is exceeded.
``trace_sample_rate`` gates stamping at the root: children always
inherit the root's decision so a trace is recorded completely or not
at all.  Rate 0 (or ``traces_max=0``) disables the plane entirely —
the worker leaves ``trace_plane`` as ``None``, specs are never
stamped, and every producer hook is a cheap ``is not None`` check
(the same contract as ``task_events_max=0``).
"""

from __future__ import annotations

import hashlib
import os
import random
import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ray_tpu._private.analysis import runtime_sanitizer
from ray_tpu._private.analysis.runtime_checks import assert_holds

# Record field indices (plain lists, same rationale as task_events).
TID = 0         # task id / call id / span id for client ops (hashable)
NAME = 1        # task or method name; "client:<op>" for client ops
KIND = 2        # "task" | "actor" | "client"
TRACE = 3       # trace_id hex
SPAN = 4        # logical span id hex (stable across retries)
PARENT = 5      # parent span id hex, None for roots
ATTEMPT = 6     # attempt number (each retry is its own record)
NODE = 7        # node index (-1 until dispatch)
WORKER = 8      # worker id once known
SUBMITTED = 9   # wall-clock timestamps (head axis), None until reached
DISPATCHED = 10
STAGED = 11     # dispatch-time arg staging kicked off (None = none)
START = 12      # execution window (worker-side, clock-aligned)
END = 13
STATE = 14      # "LIVE" | "FINISHED" | "FAILED"
ERROR = 15      # error type name for failed attempts
RETRIED = 16    # failed attempt that was retried (not terminal)
LANE = 17       # dispatch lane: None (head) | "local" | "p2p"

_LIVE, _FINISHED, _FAILED = "LIVE", "FINISHED", "FAILED"

# Per-trace span cap: one runaway fan-out must not evict every other
# trace's history; excess spans are counted, not kept.
_SPANS_PER_TRACE_CAP = 8192

_local = threading.local()


def current_parent() -> Optional[Tuple]:
    """The ambient TraceContext of the code currently running on this
    thread (None outside any traced scope)."""
    return getattr(_local, "parent", None)


@contextmanager
def parent_scope(ctx: Optional[Tuple]):
    """Install ``ctx`` as the ambient parent for the duration: any
    submission on this thread becomes its child.  No-op for None, so
    callers never need their own enablement check."""
    if ctx is None:
        yield
        return
    prev = getattr(_local, "parent", None)
    _local.parent = ctx
    try:
        yield
    finally:
        _local.parent = prev


def _new_id() -> str:
    return os.urandom(8).hex()


def new_context(rate: float,
                parent: Optional[Tuple] = None) -> Tuple:
    """TraceContext for a fresh submission.  Children join the parent's
    trace and inherit its sampling decision; roots sample at ``rate``."""
    if parent is not None:
        return (parent[0], _new_id(), parent[1], parent[3])
    sampled = rate >= 1.0 or random.random() < rate
    return (_new_id(), _new_id(), None, sampled)


def attempt_span(span: str, attempt: int) -> str:
    """Per-attempt span id, a child of the logical span ``span``."""
    return span if attempt == 0 else f"{span}#{attempt}"


def _flow_id(key: str) -> int:
    """Stable positive int for a Chrome-trace flow arrow pair."""
    return int(hashlib.md5(key.encode()).hexdigest()[:8], 16) & 0x7fffffff


class TraceAggregator:
    """Head-side span records for sampled traces, bounded by trace."""

    def __init__(self, sample_rate: Optional[float] = None,
                 max_traces: Optional[int] = None) -> None:
        if sample_rate is None or max_traces is None:
            from ray_tpu._private.config import GLOBAL_CONFIG
            if sample_rate is None:
                sample_rate = GLOBAL_CONFIG.trace_sample_rate
            if max_traces is None:
                max_traces = GLOBAL_CONFIG.traces_max
        self.sample_rate = float(sample_rate)
        self._max = int(max_traces)
        self._lock = runtime_sanitizer.wrap_lock(
            threading.Lock(), "_private.trace_plane.TraceAggregator._lock")
        self._live: Dict[Any, list] = {}
        # trace_id -> finalized span records, least recently active first
        self._traces: "OrderedDict[str, List[list]]" = OrderedDict()
        self.spans_total = 0
        self.spans_dropped = 0
        self.traces_evicted = 0
        self.client_ops_total = 0
        # Same safety valve as the task event plane: records that never
        # reach a terminal hook must not pin the live map.
        self._live_cap = max(65536, 4 * max(self._max, 1))

    # ------------------------------------------------------------------
    # context creation

    def make_context(self, parent: Optional[Tuple] = None) -> Tuple:
        if parent is None:
            parent = current_parent()
        return new_context(self.sample_rate, parent)

    # ------------------------------------------------------------------
    # producers (mirror the TaskEventAggregator hook signatures)

    def _new_rec(self, key: Any, name: str, kind: str, ctx: Tuple,
                 attempt: int, now: float) -> list:
        return [key, name, kind, ctx[0], ctx[1], ctx[2], attempt,
                -1, None, now, None, None, None, None, _LIVE, None,
                False, None]

    def on_submit_batch(self, specs: Iterable[Any]) -> None:
        """Stamp unstamped specs with a context (child of the thread's
        ambient parent, if any) and open records for sampled ones."""
        now = time.time()
        rate = self.sample_rate
        parent = current_parent()
        sampled = []
        for s in specs:
            ctx = s.trace_ctx
            if ctx is None:
                ctx = new_context(rate, parent)
                s.trace_ctx = ctx
            if ctx[3]:
                sampled.append(s)
        if not sampled:
            return
        with self._lock:
            live = self._live
            for s in sampled:
                ctx = s.trace_ctx
                live[s.task_id] = self._new_rec(
                    s.task_id, s.name, "task", ctx, s.attempt_number,
                    now)
            if len(live) > self._live_cap:
                self._trim_live_locked()

    def on_submit(self, spec: Any) -> None:
        self.on_submit_batch((spec,))

    def on_actor_call(self, call: Any, name: str,
                      node: int = -1) -> None:
        """An actor method submission (``call`` is actor._Call, already
        stamped with its trace_ctx)."""
        ctx = call.trace_ctx
        if ctx is None or not ctx[3]:
            return
        now = time.time()
        rec = self._new_rec(call.task_id, name, "actor", ctx, 0, now)
        if node >= 0:
            rec[NODE] = node
        with self._lock:
            self._live[call.task_id] = rec
            if len(self._live) > self._live_cap:
                self._trim_live_locked()

    def record_local_dispatch(self, task_id: Any, name: str,
                              ctx: Optional[Tuple], node: int,
                              now: Optional[float] = None) -> None:
        """A node's LocalScheduler admitted a worker-submitted task
        without a head round-trip: open the attempt record directly in
        the dispatched state, flagged ``lane="local"`` so the export
        draws its dispatch arrow from the NODE's lane, not the head
        scheduler lane it never crossed."""
        if ctx is None or not ctx[3]:
            return
        t = now if now is not None else time.time()
        rec = self._new_rec(task_id, name, "task", ctx, 0, t)
        rec[DISPATCHED] = t
        rec[NODE] = node
        rec[LANE] = "local"
        with self._lock:
            self._live[task_id] = rec
            if len(self._live) > self._live_cap:
                self._trim_live_locked()

    def record_p2p_span(self, task_id: Any, name: str,
                        ctx: Optional[Tuple], node: int,
                        timing: Optional[Tuple[float, float]],
                        worker: Optional[Any] = None,
                        offset: float = 0.0,
                        error_type: Optional[str] = None) -> None:
        """A peer-to-peer actor call's completion receipt: the head
        learns of the call only now, so the record opens and finalizes
        together — ``lane="p2p"`` suppresses the head-side logical and
        scheduler spans at export (the call never touched them) while
        the exec span and the worker->peer flow arrow remain."""
        if ctx is None or not ctx[3]:
            return
        now = time.time()
        rec = self._new_rec(task_id, name, "actor", ctx, 0, now)
        rec[NODE] = node
        rec[LANE] = "p2p"
        if worker is not None:
            rec[WORKER] = worker
        if timing is not None:
            rec[START] = timing[0] + offset
            rec[END] = timing[1] + offset
            rec[SUBMITTED] = rec[START]
        else:
            rec[END] = now
        if error_type is not None:
            rec[ERROR] = error_type
        with self._lock:
            self._finalize_locked(rec,
                                  _FAILED if error_type else _FINISHED)

    def record_dispatched_batch(
            self, rows: Iterable[Tuple[Any, int]]) -> None:
        """rows: (task_id, node_index) — the scheduler's decision."""
        now = time.time()
        with self._lock:
            live = self._live
            for tid, node in rows:
                rec = live.get(tid)
                if rec is not None:
                    rec[DISPATCHED] = now
                    rec[NODE] = node

    def record_staged(self, task_id: Any, node: int = -1) -> None:
        now = time.time()
        with self._lock:
            rec = self._live.get(task_id)
            if rec is not None:
                rec[STAGED] = now
                if node >= 0:
                    rec[NODE] = node

    def record_exec(self, task_id: Any,
                    timing: Optional[Tuple[float, float]],
                    node: int = -1, worker: Optional[Any] = None,
                    offset: float = 0.0) -> None:
        with self._lock:
            rec = self._live.get(task_id)
            if rec is None:
                return
            if timing is not None:
                rec[START] = timing[0] + offset
                rec[END] = timing[1] + offset
            if node >= 0:
                rec[NODE] = node
            if worker is not None:
                rec[WORKER] = worker

    def record_finished_batch(
            self,
            rows: Iterable[Tuple[Any, Optional[Tuple[float, float]],
                                 Optional[Any], int]],
            offset: float = 0.0) -> None:
        """Same row shape and clock-offset contract as the task event
        plane: (task_id, (t0, t1) | None, worker | None, node)."""
        now = time.time()
        with self._lock:
            live = self._live
            for tid, timing, wkr, node in rows:
                rec = live.pop(tid, None)
                if rec is None:
                    continue  # unsampled (or evicted) task
                if timing is not None:
                    rec[START] = timing[0] + offset
                    rec[END] = timing[1] + offset
                if rec[END] is None:
                    rec[END] = now
                if node >= 0:
                    rec[NODE] = node
                if wkr is not None:
                    rec[WORKER] = wkr
                self._finalize_locked(rec, _FINISHED)

    def record_failed(self, task_id: Any, error_type: str) -> None:
        """Terminal failure.  Unlike the task event plane this does not
        synthesize a record — an unsampled task hits this hook on every
        failure and must stay free."""
        now = time.time()
        with self._lock:
            rec = self._live.pop(task_id, None)
            if rec is None:
                return
            rec[ERROR] = error_type
            if rec[END] is None:
                rec[END] = now
            self._finalize_locked(rec, _FAILED)

    def record_retry(self, old_task_id: Any, error_type: str,
                     spec: Any) -> None:
        """Finalize the failed attempt (flagged retried) and open the
        next attempt's record under the SAME logical span — the spec's
        trace_ctx is unchanged by retry, only task_id/attempt mutate."""
        ctx = getattr(spec, "trace_ctx", None)
        now = time.time()
        with self._lock:
            rec = self._live.pop(old_task_id, None)
            if rec is not None:
                rec[ERROR] = error_type
                rec[RETRIED] = True
                if rec[END] is None:
                    rec[END] = now
                self._finalize_locked(rec, _FAILED)
            if ctx is not None and ctx[3]:
                self._live[spec.task_id] = self._new_rec(
                    spec.task_id, spec.name, "task", ctx,
                    spec.attempt_number, now)

    @contextmanager
    def client_span(self, op: str):
        """Span for one ray:// client operation.  Roots a fresh trace
        (sampled at the knob rate) and installs it as the thread's
        parent so the head-side submission it triggers becomes its
        child."""
        ctx = self.make_context(parent=None)
        t0 = time.time()
        with parent_scope(ctx):
            try:
                yield ctx
            finally:
                t1 = time.time()
                with self._lock:
                    self.client_ops_total += 1
                    if ctx[3]:
                        rec = self._new_rec(ctx[1], f"client:{op}",
                                            "client", ctx, 0, t0)
                        rec[START] = t0
                        rec[END] = t1
                        self._finalize_locked(rec, _FINISHED)

    # ------------------------------------------------------------------
    # internals (caller holds self._lock)

    def _finalize_locked(self, rec: list, state: str) -> None:
        assert_holds(self._lock, "TraceAggregator ring")
        rec[STATE] = state
        trace_id = rec[TRACE]
        spans = self._traces.get(trace_id)
        if spans is None:
            if self._max and len(self._traces) >= self._max:
                self._traces.popitem(last=False)
                self.traces_evicted += 1
            spans = self._traces[trace_id] = []
        else:
            self._traces.move_to_end(trace_id)
        if len(spans) >= _SPANS_PER_TRACE_CAP:
            self.spans_dropped += 1
            return
        spans.append(rec)
        self.spans_total += 1

    def _trim_live_locked(self) -> None:
        assert_holds(self._lock, "TraceAggregator live table")
        live = self._live
        while len(live) > self._live_cap:
            live.pop(next(iter(live)))

    # ------------------------------------------------------------------
    # consumers (state API / CLI / dashboard / metrics)

    def list_traces(self) -> List[Dict[str, Any]]:
        """One row per resident trace, most recently active first."""
        with self._lock:
            items = [(t, list(rs)) for t, rs in self._traces.items()]
            live = [list(r) for r in self._live.values()]
        agg: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        for trace_id, recs in items:
            agg[trace_id] = {"trace_id": trace_id, "recs": recs,
                             "live_spans": 0}
        for rec in live:
            row = agg.setdefault(rec[TRACE],
                                 {"trace_id": rec[TRACE], "recs": [],
                                  "live_spans": 0})
            row["live_spans"] += 1
            row["recs"].append(rec)
        rows = []
        for row in agg.values():
            recs = row.pop("recs")
            roots = [r for r in recs if r[PARENT] is None]
            subs = [r[SUBMITTED] for r in recs
                    if r[SUBMITTED] is not None]
            ends = [r[END] for r in recs if r[END] is not None]
            row["spans"] = len(recs) - row["live_spans"]
            row["root"] = roots[0][NAME] if roots else None
            row["failed"] = sum(1 for r in recs
                                if r[STATE] == _FAILED
                                and not r[RETRIED])
            row["first_ts"] = min(subs) if subs else None
            row["last_ts"] = max(ends) if ends else None
            rows.append(row)
        rows.reverse()
        return rows

    def trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """Chrome-trace/Perfetto events for one trace: a driver lane of
        logical spans, a scheduler lane of per-attempt decision spans,
        one exec lane per (node, worker), dispatch flow arrows from the
        scheduler lane to the exec lane, and spawn flow arrows from a
        parent's exec span to each child's exec span.  Prefix match on
        ``trace_id`` is allowed (CLI id handling idiom)."""
        with self._lock:
            recs = [list(r) for t, rs in self._traces.items()
                    if t.startswith(trace_id) for r in rs]
            recs.extend(list(r) for r in self._live.values()
                        if r[TRACE].startswith(trace_id))
        return _export(recs)

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "spans_total": self.spans_total,
                "spans_dropped": self.spans_dropped,
                "traces_evicted": self.traces_evicted,
                "client_ops_total": self.client_ops_total,
                "traces_resident": len(self._traces),
                "live_spans": len(self._live),
            }


# ----------------------------------------------------------------------
# Perfetto export

def _hex(tid: Any) -> str:
    h = getattr(tid, "hex", None)
    return h() if callable(h) else str(tid)


def _export(recs: List[list]) -> List[Dict[str, Any]]:
    events: List[Dict[str, Any]] = []
    named_pids = set()
    lanes: Dict[Tuple[int, Any], int] = {}
    lanes_per_pid: Dict[int, int] = {}
    # (span, attempt) -> (pid, tid) of the attempt's exec event, for
    # spawn flow arrows in the second pass
    placed: Dict[Tuple[str, int], Tuple[int, int]] = {}

    def _pid_meta(pid: int) -> None:
        if pid in named_pids:
            return
        named_pids.add(pid)
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0,
                       "args": {"name": ("head" if pid == 0
                                         else f"node {pid}")}})
        if pid == 0:
            events.append({"name": "thread_name", "ph": "M", "pid": 0,
                           "tid": 0, "args": {"name": "driver"}})
            events.append({"name": "thread_name", "ph": "M", "pid": 0,
                           "tid": 1, "args": {"name": "scheduler"}})
            lanes_per_pid[0] = 1  # head exec lanes start at tid 2

    def _lane(pid: int, worker: Any, label: Optional[str] = None) -> int:
        key = (pid, worker)
        t = lanes.get(key)
        if t is None:
            t = lanes_per_pid.get(pid, 0) + 1
            lanes_per_pid[pid] = t
            lanes[key] = t
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": t,
                           "args": {"name": label
                                    or f"worker {worker}"}})
        return t

    _pid_meta(0)
    by_span: "OrderedDict[str, List[list]]" = OrderedDict()
    for rec in recs:
        by_span.setdefault(rec[SPAN], []).append(rec)

    for span, srecs in by_span.items():
        srecs.sort(key=lambda r: r[ATTEMPT])
        r0 = srecs[0]
        rN = srecs[-1]
        base = {"trace_id": r0[TRACE], "span_id": span,
                "parent_span_id": r0[PARENT], "kind": r0[KIND]}
        subs = [r[SUBMITTED] for r in srecs
                if r[SUBMITTED] is not None]
        ends = [r[END] for r in srecs if r[END] is not None]
        t_lo = min(subs) if subs else None
        t_hi = (max(ends) if ends
                else (time.time() if t_lo is not None else None))
        span_p2p = all(r[LANE] == "p2p" for r in srecs)
        if t_lo is not None and t_hi is not None and not span_p2p:
            # the logical span: driver submit -> resolve. A purely
            # peer-to-peer call never touched the head lane — emitting
            # a head span for it would invent a round-trip that the
            # whole p2p plane exists to remove.
            events.append({"name": r0[NAME], "cat": "span", "ph": "X",
                           "pid": 0, "tid": 0, "ts": t_lo * 1e6,
                           "dur": max(t_hi - t_lo, 0.0) * 1e6,
                           "args": dict(base, attempts=len(srecs),
                                        state=rN[STATE],
                                        error_type=rN[ERROR])})
        for rec in srecs:
            aspan = attempt_span(span, rec[ATTEMPT])
            args = {"trace_id": rec[TRACE], "span_id": aspan,
                    "parent_span_id": span, "attempt": rec[ATTEMPT],
                    "task_id": _hex(rec[TID])}
            sub, dsp = rec[SUBMITTED], rec[DISPATCHED]
            stg = rec[STAGED]
            node = rec[NODE]
            lane = rec[LANE]
            pid = node if isinstance(node, int) and node >= 0 else 0
            _pid_meta(pid)
            sched_src = None  # (pid, tid) the dispatch arrow leaves from
            if sub is not None and dsp is not None and dsp >= sub:
                if lane == "local":
                    # admitted by the NODE's LocalScheduler: its
                    # decision span lives on the node, not the head
                    ltid = _lane(pid, "__lsched__", "local scheduler")
                    sched_src = (pid, ltid)
                    events.append({"name": f"lsched:{rec[NAME]}",
                                   "cat": "sched", "ph": "X",
                                   "pid": pid, "tid": ltid,
                                   "ts": sub * 1e6,
                                   "dur": (dsp - sub) * 1e6,
                                   "args": dict(args, node_chosen=node,
                                                lane="local")})
                elif lane is None:
                    sched_src = (0, 1)
                    events.append({"name": f"sched:{rec[NAME]}",
                                   "cat": "sched", "ph": "X", "pid": 0,
                                   "tid": 1, "ts": sub * 1e6,
                                   "dur": (dsp - sub) * 1e6,
                                   "args": dict(args, node_chosen=node,
                                                staged=stg is not None)})
            t0, t1 = rec[START], rec[END]
            if t0 is not None and t1 is not None:
                wkr = rec[WORKER] if rec[WORKER] is not None else 0
                tid = _lane(pid, wkr)
                placed[(span, rec[ATTEMPT])] = (pid, tid)
                events.append({"name": f"exec:{rec[NAME]}",
                               "cat": "exec", "ph": "X", "pid": pid,
                               "tid": tid, "ts": t0 * 1e6,
                               "dur": max(t1 - t0, 0.0) * 1e6,
                               "args": dict(args, worker_id=str(wkr),
                                            lane=lane or "head")})
                anchor = dsp if dsp is not None else sub
                # p2p calls get their arrow from the CALLER's exec span
                # (the spawn pass below, named "p2p"); the head never
                # dispatched them, so no head-anchored arrow exists
                if anchor is not None and lane != "p2p":
                    src = sched_src if sched_src is not None else (0, 1)
                    fid = _flow_id(aspan + ":d")
                    events.append({"ph": "s", "cat": "flow",
                                   "name": ("local_dispatch"
                                            if lane == "local"
                                            else "dispatch"),
                                   "id": fid,
                                   "pid": src[0], "tid": src[1],
                                   "ts": anchor * 1e6})
                    events.append({"ph": "f", "bp": "e", "cat": "flow",
                                   "name": ("local_dispatch"
                                            if lane == "local"
                                            else "dispatch"),
                                   "id": fid, "pid": pid, "tid": tid,
                                   "ts": t0 * 1e6})
            if rec[STATE] == _FAILED:
                kind = "retry" if rec[RETRIED] else "failed"
                events.append({"name": f"{rec[NAME]}:{kind}",
                               "ph": "i", "s": "p", "pid": pid,
                               "tid": 0,
                               "ts": ((t1 if t1 is not None
                                       else time.time()) * 1e6),
                               "args": dict(args,
                                            error_type=rec[ERROR])})

    # spawn flow arrows: parent exec span -> child exec span
    for span, srecs in by_span.items():
        parent = srecs[0][PARENT]
        if parent is None or parent not in by_span:
            continue
        child = next((r for r in srecs if (span, r[ATTEMPT]) in placed),
                     None)
        if child is None or child[SUBMITTED] is None:
            continue
        # the parent attempt lane (last placed attempt wins)
        ppl = None
        for prec in by_span[parent]:
            ppl = placed.get((parent, prec[ATTEMPT]), ppl)
        if ppl is None:
            continue
        fid = _flow_id(span + ":p")
        cpid, ctid = placed[(span, child[ATTEMPT])]
        # a p2p child's arrow IS its dispatch record: caller exec lane
        # straight to the peer exec lane, no head hop in between
        aname = "p2p" if child[LANE] == "p2p" else "spawn"
        events.append({"ph": "s", "cat": "flow", "name": aname,
                       "id": fid, "pid": ppl[0], "tid": ppl[1],
                       "ts": child[SUBMITTED] * 1e6})
        events.append({"ph": "f", "bp": "e", "cat": "flow",
                       "name": aname, "id": fid, "pid": cpid,
                       "tid": ctid, "ts": child[START] * 1e6})
    return events
