"""Scheduling benchmark harness — the 5 BASELINE configs + the north star.

Each config builds a task DAG in the array form the scheduler kernels
consume (see scheduler/kernels.py) and measures the AGGREGATE SCHEDULING
OVERHEAD: the time the jitted instant-completion tick kernel needs to
drive the whole DAG from submitted to done — every ready-set computation,
every node-assignment decision, every dependency-wave propagation — with
task execution simulated as instantaneous. This isolates exactly what the
reference measures as scheduler throughput (its per-task
ClusterTaskManager/LocalTaskManager C++ event-loop path, amortized by
lease reuse; see SURVEY.md §3.2) and what BASELINE.md's north star bounds:
1M-task fan-out DAG < 10 ms aggregate on one TPU chip.

Configs (BASELINE.md):
  1. fanout:      10 k no-op tasks, zero deps
  2. map_reduce:  100 k tasks, 2-level ObjectRef deps (north-star shape at
                  1 M tasks = ``north_star``)
  3. pipeline:    map_batches-style wide DAG (stages of uniform demand)
  4. actor_heavy: 1 k actors × 1 k calls (per-actor ordered chains — the
                  lease-reuse path; deep narrow DAG, many ticks)
  5. ppo:         rollout/learn DAG with heterogeneous demands (CPU
                  rollouts feeding TPU learner tasks, placement-grouped)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ray_tpu._private.scheduler import kernels
from ray_tpu._private.scheduler.kernels import DONE, WAITING


@dataclasses.dataclass
class BenchGraph:
    name: str
    indeg: np.ndarray      # [C] int32
    cls: np.ndarray        # [C] int32
    demands: np.ndarray    # [K, R] float32
    src: np.ndarray        # [E] int32
    dst: np.ndarray        # [E] int32 (must be sorted ascending)
    cap: np.ndarray        # [N, R] float32
    max_ticks: int
    pin: Optional[np.ndarray] = None  # [C] int32, -1 = schedule normally


def _nodes(n: int, cpu: float, tpu: float = 0.0) -> np.ndarray:
    cap = np.zeros((n, 4), dtype=np.float32)
    cap[:, 0] = cpu
    cap[:, 1] = tpu
    return cap


def build_fanout(num_tasks: int = 10_000, num_nodes: int = 64) -> BenchGraph:
    """Config 1: embarrassingly parallel no-op tasks, zero deps."""
    per_node = -(-num_tasks // num_nodes)
    return BenchGraph(
        name=f"fanout_{num_tasks}",
        indeg=np.zeros(num_tasks, dtype=np.int32),
        cls=np.zeros(num_tasks, dtype=np.int32),
        demands=np.asarray([[1, 0, 0, 0]], dtype=np.float32),
        src=np.zeros(0, dtype=np.int32),
        dst=np.zeros(0, dtype=np.int32),
        cap=_nodes(num_nodes, float(per_node)),
        max_ticks=4,
    )


def build_map_reduce(num_tasks: int = 100_000, fan_in: int = 100,
                     num_nodes: int = 64) -> BenchGraph:
    """Config 2 / north star: 2-level DAG. num_tasks total; the last
    num_tasks/(fan_in+1) tasks are reducers, each depending on fan_in maps."""
    num_reduce = num_tasks // (fan_in + 1)
    num_map = num_tasks - num_reduce
    c = num_tasks
    indeg = np.zeros(c, dtype=np.int32)
    # reducer j occupies slot num_map + j and reads maps [j*fan_in, ...)
    rj = np.arange(num_reduce, dtype=np.int64)
    starts = rj * fan_in
    src = (starts[:, None] + np.arange(fan_in)[None, :]).reshape(-1)
    src = np.minimum(src, num_map - 1).astype(np.int32)
    dst = np.repeat(num_map + rj, fan_in).astype(np.int32)
    np.add.at(indeg, dst, 1)
    per_node = -(-num_map // num_nodes)
    return BenchGraph(
        name=f"map_reduce_{num_tasks}",
        indeg=indeg,
        cls=np.zeros(c, dtype=np.int32),
        demands=np.asarray([[1, 0, 0, 0]], dtype=np.float32),
        src=src, dst=dst,
        cap=_nodes(num_nodes, float(per_node)),
        max_ticks=8,
    )


def build_pipeline(num_stages: int = 4, width: int = 25_000,
                   num_nodes: int = 64) -> BenchGraph:
    """Config 3: map_batches-style pipeline — ``width`` parallel block
    chains through ``num_stages`` uniform-demand operators."""
    c = num_stages * width
    idx = np.arange(c, dtype=np.int64)
    stage = idx // width
    indeg = (stage > 0).astype(np.int32)
    has_edge = stage < num_stages - 1
    src = idx[has_edge].astype(np.int32)
    dst = (idx[has_edge] + width).astype(np.int32)
    per_node = -(-width // num_nodes)
    return BenchGraph(
        name=f"pipeline_{num_stages}x{width}",
        indeg=indeg,
        cls=np.zeros(c, dtype=np.int32),
        demands=np.asarray([[1, 0, 0, 0]], dtype=np.float32),
        src=src, dst=dst,
        cap=_nodes(num_nodes, float(per_node)),
        max_ticks=num_stages + 2,
    )


def build_actor_heavy(num_actors: int = 1000, calls: int = 1000,
                      num_nodes: int = 64) -> BenchGraph:
    """Config 4: 1k actors × 1k calls. Models the reference's actor path
    faithfully: actor CREATION is a scheduled task (resource-bearing);
    method CALLS are pinned to the actor's node and consume no scheduler
    resources — in the reference, calls go directly to the actor's leased
    worker over its ordered queue and never re-enter the scheduler (the
    lease-reuse mechanism that makes actor calls cheap). Each call still
    depends on its actor's creation completing, so the kernel processes
    creation wave -> 1M-call pinned assignment wave."""
    c = num_actors * (calls + 1)
    # slots [0, num_actors) = creations; rest = calls grouped by actor
    creation = np.arange(num_actors, dtype=np.int64)
    call_idx = np.arange(num_actors * calls, dtype=np.int64)
    call_actor = call_idx // calls
    call_slot = num_actors + call_idx
    indeg = np.zeros(c, dtype=np.int32)
    indeg[call_slot] = 1
    src = call_actor.astype(np.int32)          # creation -> each call
    dst = call_slot.astype(np.int32)           # sorted ascending
    cls = np.zeros(c, dtype=np.int32)
    cls[call_slot] = 1                         # calls: zero-demand class
    pin = np.full(c, -1, dtype=np.int32)
    pin[call_slot] = (call_actor % num_nodes).astype(np.int32)
    per_node = -(-num_actors // num_nodes)
    return BenchGraph(
        name=f"actor_{num_actors}x{calls}",
        indeg=indeg,
        cls=cls,
        demands=np.asarray([[1, 0, 0, 0], [0, 0, 0, 0]], dtype=np.float32),
        src=src, dst=dst,
        cap=_nodes(num_nodes, float(per_node)),
        max_ticks=4,
        pin=pin,
    )


def build_ppo(num_rollout: int = 8000, num_learn: int = 80,
              rounds: int = 10, num_nodes: int = 16) -> BenchGraph:
    """Config 5: PPO-style rounds — a wave of CPU rollout tasks feeding a
    wave of TPU learner tasks, repeated; heterogeneous demand classes.

    The learner group is placement-grouped like the reference's RLlib
    LearnerGroup (ray: rllib/core/learner/ — PG of one TPU bundle per
    learner, PACK): the bundle bin-pack solve (pack_bundles_np — the
    GcsPlacementGroupScheduler analog) reserves learner slots at build
    time, and every learner task is PINNED to its bundle's node — the
    per-call fast path for placement-grouped work, with resources held
    by the reservation rather than re-acquired per task."""
    per_round = num_rollout + num_learn
    c = per_round * rounds
    cls = np.zeros(c, dtype=np.int32)
    indeg = np.zeros(c, dtype=np.int32)
    srcs, dsts = [], []
    fan = num_rollout // num_learn
    for r in range(rounds):
        base = r * per_round
        learn0 = base + num_rollout
        cls[learn0:learn0 + num_learn] = 1
        rollouts = base + np.arange(num_rollout, dtype=np.int64)
        learners = learn0 + (np.arange(num_rollout, dtype=np.int64) // fan)
        srcs.append(rollouts)
        dsts.append(learners)
        np.add.at(indeg, learners, 1)
        if r + 1 < rounds:
            next_rollouts = base + per_round + np.arange(
                num_rollout, dtype=np.int64)
            feeders = learn0 + (np.arange(num_rollout, dtype=np.int64)
                                % num_learn)
            srcs.append(feeders)
            dsts.append(next_rollouts)
            np.add.at(indeg, next_rollouts, 1)
    src = np.concatenate(srcs).astype(np.int32)
    dst = np.concatenate(dsts).astype(np.int32)
    cap = _nodes(num_nodes, float(-(-num_rollout // num_nodes)),
                 tpu=float(-(-num_learn // num_nodes)))

    # placement-group the learners: PACK one 1-TPU bundle per learner,
    # pin learner task j (every round) to its bundle's node. Bundle
    # resources are held by the reservation, so the learner class demand
    # is zero per-call (kernel pin-path convention, kernels.py).
    from ray_tpu._private.scheduler.kernels import pack_bundles_np

    bundle_demands = np.tile(np.asarray([[0, 1, 0, 0]], np.float32),
                             (num_learn, 1))
    sol = pack_bundles_np(bundle_demands, cap.copy(), cap, "PACK")
    if sol is None:
        raise RuntimeError("ppo bench: learner placement group cannot fit")
    pin = np.full(c, -1, dtype=np.int32)
    for r in range(rounds):
        learn0 = r * per_round + num_rollout
        pin[learn0:learn0 + num_learn] = sol
    return BenchGraph(
        name=f"ppo_{rounds}r",
        indeg=indeg,
        cls=cls,
        demands=np.asarray([[1, 0, 0, 0], [0, 0, 0, 0]], dtype=np.float32),
        src=src, dst=dst,
        cap=cap,
        max_ticks=2 * rounds + 4,
        pin=pin,
    )


def build_north_star(num_tasks: int = 1_000_000,
                     num_nodes: int = 64) -> BenchGraph:
    """BASELINE.json north star: 1M-task fan-out DAG."""
    g = build_fanout(num_tasks=num_tasks, num_nodes=num_nodes)
    g.name = f"north_star_fanout_{num_tasks}"
    return g


def build_north_star_waves(num_tasks: int = 1_000_000,
                           num_waves: int = 64,
                           num_nodes: int = 64) -> BenchGraph:
    """North-star honesty companion: the same 1M tasks admitted over
    ``num_waves`` dependency waves instead of one flat fan-out. Wave w
    gates on wave w-1's first task, so the kernel must run a full
    ready-set/admission tick PER WAVE — the multi-tick admission cost a
    single-wave fan-out never shows. Capacity is sized to one wave, not
    the whole DAG."""
    per_wave = num_tasks // num_waves
    num_tasks = per_wave * num_waves
    c = num_tasks
    idx = np.arange(c, dtype=np.int64)
    wave = idx // per_wave
    indeg = (wave > 0).astype(np.int32)
    # every task of wave w>0 depends on wave w-1's FIRST task; dst is
    # naturally ascending in this wave-major layout
    has_edge = wave > 0
    src = ((wave[has_edge] - 1) * per_wave).astype(np.int32)
    dst = idx[has_edge].astype(np.int32)
    per_node = -(-per_wave // num_nodes)
    return BenchGraph(
        name=f"north_star_waves_{num_tasks}x{num_waves}",
        indeg=indeg,
        cls=np.zeros(c, dtype=np.int32),
        demands=np.asarray([[1, 0, 0, 0]], dtype=np.float32),
        src=src, dst=dst,
        cap=_nodes(num_nodes, float(per_node)),
        max_ticks=num_waves + 2,
    )


CONFIGS = {
    "fanout": build_fanout,
    "map_reduce": build_map_reduce,
    "pipeline": build_pipeline,
    "actor_heavy": build_actor_heavy,
    "ppo": build_ppo,
    "north_star": build_north_star,
    "north_star_waves": build_north_star_waves,
}


# ----------------------------------------------------------------------
# Runner
# ----------------------------------------------------------------------

def _device_state(g: BenchGraph):
    import jax.numpy as jnp

    pin = (g.pin if g.pin is not None
           else np.full(len(g.indeg), -1, dtype=np.int32))
    # the edge-fire segment_sum assumes dst sorted ascending; sort into
    # locals (never mutate the caller's BenchGraph — callers may hold
    # edge-index views built before this call)
    order = np.argsort(g.dst, kind="stable")
    src, dst = g.src[order], g.dst[order]
    return (
        jnp.full(len(g.indeg), WAITING, dtype=jnp.int8),
        jnp.asarray(g.indeg),
        jnp.asarray(g.cls),
        jnp.asarray(pin),
        jnp.asarray(g.demands),
        jnp.asarray(g.cap),       # avail starts at capacity
        jnp.asarray(g.cap),
        jnp.asarray(src),
        jnp.asarray(dst),
        jnp.zeros(len(src), dtype=bool),
    )


def run_graph(g: BenchGraph, threshold: float = 0.99, repeats: int = 5,
              retries: int = 3, warm_only: bool = False,
              k_lo: int = 1, k_hi: int = 9) -> Dict[str, float]:
    """Measure true per-DAG scheduling time on a hostile transport.

    The device tunnel in this environment (a) oscillates between ~0.05 ms
    and ~100 ms per host round-trip and (b) acks block_until_ready BEFORE
    work completes, so wall-clocking a single dispatch is meaningless.
    Protocol (see kernels._jit_bench):
      - one program runs K whole-DAG drives chained by true data
        dependence (no CSE/hoisting possible);
      - completion is forced by FETCHING the tick-count scalar (the only
        honest completion signal);
      - T(K) = round_trip + K * drive; measure min-of-N at K=k_lo and
        K=k_hi and difference to cancel the round trip and fetch cost.
    """
    import jax

    num_classes = int(g.demands.shape[0])
    st = _device_state(g)
    jax.block_until_ready(st)

    def timed(k: int):
        t0 = time.perf_counter()
        total, state = kernels.jax_bench(
            *st, num_classes=num_classes, threshold=threshold,
            max_ticks=g.max_ticks, k_reps=k)
        total = int(total)  # D2H fetch: forces genuine completion
        dt = time.perf_counter() - t0
        return dt, total, state

    def retrying(fn, *a):
        last = None
        for _ in range(retries):
            try:
                return fn(*a)
            except Exception as e:  # transient device faults
                last = e
                time.sleep(0.5)
        raise last

    # warmup / compile both K variants
    _, total_lo, state = retrying(timed, k_lo)
    if not bool((np.asarray(state) == DONE).all()):
        raise RuntimeError(
            f"bench graph {g.name} did not complete in {g.max_ticks} ticks")
    ticks = total_lo // k_lo
    if warm_only:
        retrying(timed, k_hi)
        return {"name": g.name, "tasks": len(g.indeg), "ticks": ticks,
                "scheduling_ms": float("nan"), "tasks_per_sec": float("nan")}
    retrying(timed, k_hi)

    # Sample (lo, hi) back-to-back so both land in the same congestion
    # window, and take the MEDIAN of the positive per-pair differences:
    # a min would keep pairs where the window flipped between the two
    # samples (arbitrarily small diffs), a mean would keep slow-window
    # inflation; the median of >=5 pairs lands on a clean intra-window
    # measurement.
    diffs = []
    for _ in range(max(repeats, 5)):
        t_lo = retrying(timed, k_lo)[0]
        t_hi = retrying(timed, k_hi)[0]
        diffs.append((t_hi - t_lo) / (k_hi - k_lo))
    positive = sorted(d for d in diffs if d > 0)
    if not positive:
        # a failed measurement must never be reported as a (record-
        # setting) success: every (hi, lo) pair was inverted by transport
        # noise, so there is no honest number to report
        raise RuntimeError(
            f"bench {g.name}: no positive (K_hi - K_lo) timing pair over "
            f"{len(diffs)} samples; transport too noisy to measure")
    per_drive = positive[len(positive) // 2]
    n = len(g.indeg)
    return {
        "name": g.name,
        "tasks": n,
        "ticks": ticks,
        "scheduling_ms": per_drive * 1e3,
        "tasks_per_sec": n / per_drive,
    }


def settle_device(threshold_ms: float = 2.0, timeout_s: float = 30.0) -> None:
    """Wait until device dispatch latency returns to its floor.

    Compilation activity leaves the device/transport path congested for a
    while afterwards (~100 ms per dispatch instead of ~0.1 ms on the
    tunneled chip here); measuring during that window would report
    transport noise, not kernel time. Spin a trivial jitted dispatch until
    it is consistently fast (or give up after timeout and measure anyway).
    """
    import jax
    import jax.numpy as jnp

    probe = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8)
    jax.block_until_ready(probe(x))
    deadline = time.perf_counter() + timeout_s
    fast = 0
    while time.perf_counter() < deadline and fast < 3:
        t0 = time.perf_counter()
        jax.block_until_ready(probe(x))
        if (time.perf_counter() - t0) * 1e3 < threshold_ms:
            fast += 1
        else:
            fast = 0
            time.sleep(0.2)


def run_all(sizes: str = "full") -> Dict[str, Dict[str, float]]:
    """sizes: 'full' = BASELINE sizes, 'smoke' = tiny CI sizes."""
    if sizes == "smoke":
        graphs = [
            build_fanout(1000, 8),
            build_map_reduce(2020, 100, 8),
            build_pipeline(3, 500, 8),
            build_actor_heavy(50, 20, 8),
            build_ppo(200, 10, 3, 4),
            build_north_star(10_000, 8),
        ]
    else:
        graphs = [
            build_fanout(),
            build_map_reduce(),
            build_pipeline(),
            build_actor_heavy(),
            build_ppo(),
            build_north_star(),
        ]
    # Phase 1: compile-warm every config, THEN time. Interleaving compiles
    # with timed runs leaves the device path congested (see settle_device).
    for g in graphs:
        run_graph(g, warm_only=True)
    return {g.name: run_graph(g) for g in graphs}
