"""Core worker: task submission + execution engine + object access.

Reference surfaces: ray src/ray/core_worker/core_worker.cc (CoreWorker:
SubmitTask/Put/Get/Wait, ownership), task_manager.cc (TaskManager:
pending tasks, retries, lineage), python/ray/_private/worker.py (the
module-level API: init/shutdown/get/put/wait/cancel).

Single-process architecture (phase P1): the driver and all workers share
one process; workers are threads in an executor pool; the scheduler is
pluggable (event-driven oracle or device-tensor scheduler). Multi-process
node runtime (phase P3) swaps the executor pool for forked worker
processes + the shm object store, keeping this module's semantics.
"""

from __future__ import annotations

import collections
import heapq
from collections import OrderedDict
import logging
import os
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu import exceptions as rex
from ray_tpu._private.analysis import runtime_sanitizer
from ray_tpu._private.chaos import get_controller as _chaos_controller
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import (ActorID, JobID, NodeID, ObjectID, TaskID,
                                  WorkerID, _Counter)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import MemoryStore
from ray_tpu._private.ref_counting import ReferenceCounter
from ray_tpu._private.scheduler.base import PendingTask, SchedulerBase
from ray_tpu._private.scheduler.local import EventScheduler, NodeState
from ray_tpu._private.task_spec import TaskSpec, TaskType
from ray_tpu._private import trace_plane

logger = logging.getLogger(__name__)

global_worker: Optional["Worker"] = None
_init_lock = threading.Lock()
_gc_tuned = False
_gc_saved_threshold = (700, 10, 10)


def _noop_exec(task, node_index) -> None:
    """Placeholder PendingTask.execute (dispatch goes through the
    worker's dispatcher, not the task) — shared, not a per-task lambda."""


def _task_error_type(exc: BaseException) -> str:
    """Error-type label for task event records: unwrap one chaining
    level so TaskError(ValueError) reports "ValueError", not the
    wrapper."""
    cause = getattr(exc, "__cause__", None)
    return type(cause).__name__ if cause is not None else type(exc).__name__


class _TaskContext(threading.local):
    """Per-thread execution context (reference: WorkerContext)."""

    def __init__(self):
        self.task_id: Optional[TaskID] = None
        self.put_counter = 0
        self.actor_id: Optional[ActorID] = None
        self.cancel_requested = False


class TaskManager:
    """Owner-side pending-task table: retries + lineage.

    Reference: src/ray/core_worker/task_manager.cc — AddPendingTask,
    retry-on-failure resubmission, lineage kept while returned objects
    remain in scope (capped by max_lineage_bytes).
    """

    def __init__(self, worker: "Worker"):
        self._worker = worker
        self._lock = threading.RLock()
        self._pending: Dict[TaskID, Tuple[TaskSpec, List[ObjectID]]] = {}
        # original-id -> current retry id for in-flight retries
        self._pending_origin: Dict[TaskID, TaskID] = {}
        self._lineage: Dict[TaskID, TaskSpec] = {}
        self._lineage_bytes = 0
        self._lineage_cap = GLOBAL_CONFIG.entry("max_lineage_bytes")
        self.num_retries = 0

    def add_pending(self, spec: TaskSpec, deps: List[ObjectID]) -> None:
        with self._lock:
            self._pending[spec.task_id] = (spec, deps)

    def add_pending_batch(self, specs: List[TaskSpec]) -> None:
        """One lock hold; deps must already be memoized on each spec."""
        with self._lock:
            pending = self._pending
            for spec in specs:
                pending[spec.task_id] = (spec, spec._deps_memo)

    def filter_not_pending(self, object_ids: List[ObjectID]) -> List[ObjectID]:
        """Ids whose producing task is NOT in flight (one lock hold) —
        the recovery path's bulk pre-filter."""
        with self._lock:
            pending = self._pending
            origin = self._pending_origin
            out = []
            for oid in object_ids:
                tid = oid.task_id()
                if tid in pending or origin.get(tid) in pending:
                    continue
                out.append(oid)
            return out

    def rekey_pending(self, old_id: TaskID, spec: TaskSpec,
                      deps: List[ObjectID]) -> None:
        """A retry gets a fresh attempt id: move the pending entry (the
        old id would otherwise leak and shadow lineage lookups forever)
        and remember the ORIGINAL id — return ids derive from it, and
        recovery/lineage must resolve through it."""
        with self._lock:
            self._pending.pop(old_id, None)
            self._pending[spec.task_id] = (spec, deps)
            rr = getattr(spec, "_retry_return_ids", None)
            origin = rr[0].task_id() if rr else old_id
            self._pending_origin[origin] = spec.task_id
        plane = self._worker.qos_plane
        if plane is not None:
            plane.note_rekeyed(old_id, spec.task_id)

    def pending_spec_for_object(self, oid: ObjectID) -> Optional[TaskSpec]:
        """The in-flight spec that will produce oid, or None if its
        task already completed (return ids derive from the ORIGINAL
        task id, so retries resolve through _pending_origin)."""
        with self._lock:
            tid = oid.task_id()
            tid = self._pending_origin.get(tid, tid)
            entry = self._pending.get(tid)
            return entry[0] if entry else None

    def complete(self, task_id: TaskID) -> None:
        with self._lock:
            self._complete_locked(task_id)

    def complete_batch(self, task_ids: List[TaskID]) -> None:
        """One lock hold for a drain-loop's worth of completions (the
        fast-path executor defers these — lineage bookkeeping never
        gates scheduling, unlike the finished-notification)."""
        with self._lock:
            for task_id in task_ids:
                self._complete_locked(task_id)

    def complete_batch_with_refs(self, pairs,
                                 has_reference) -> None:
        """Deferred completion for the fast path: ``pairs`` is
        [(task_id, return_oid)]. Because these completions run AFTER
        the object-ready notification, the return ref may already be
        dead — its out-of-scope eviction would then have run before
        this lineage insert, stranding the spec in ``_lineage``
        forever. Checking liveness under the table lock closes that
        window (a concurrent eviction blocks on this same lock)."""
        plane = self._worker.qos_plane
        with self._lock:
            for task_id, oid in pairs:
                entry = self._pending.pop(task_id, None)
                if entry is None:
                    continue
                if plane is not None:
                    plane.note_done(task_id)
                spec, _ = entry
                rr = getattr(spec, "_retry_return_ids", None)
                key = rr[0].task_id() if rr else task_id
                self._pending_origin.pop(key, None)
                if not has_reference(oid):
                    continue  # returns already dead: nothing to recover
                if key not in self._lineage:
                    self._lineage_bytes += 256
                self._lineage[key] = spec
                if self._lineage_bytes > self._lineage_cap.value:
                    self._evict_lineage_locked()

    def _complete_locked(self, task_id: TaskID) -> None:
        entry = self._pending.pop(task_id, None)
        if entry is not None:
            plane = self._worker.qos_plane
            if plane is not None:
                plane.note_done(task_id)
            spec, _ = entry
            # retain lineage for reconstruction while returns in
            # scope — keyed by the id the RETURN ids derive from, so
            # recovery of a retried/reconstructed task's outputs
            # still finds the spec
            rr = getattr(spec, "_retry_return_ids", None)
            key = rr[0].task_id() if rr else task_id
            self._pending_origin.pop(key, None)
            if key not in self._lineage:  # overwrites don't grow
                self._lineage_bytes += 256  # coarse estimate per spec
            self._lineage[key] = spec
            if self._lineage_bytes > self._lineage_cap.value:
                self._evict_lineage_locked()

    def should_retry(self, spec: TaskSpec, exc: BaseException) -> bool:
        if spec.attempt_number >= spec.max_retries:
            return False
        if isinstance(exc, (rex.WorkerCrashedError, rex.OutOfMemoryError,
                            rex.NodeDiedError, rex.TaskTimeoutError)):
            return True  # system failures always retriable up to max_retries
        retry_exc = spec.retry_exceptions
        if retry_exc is True:
            return True
        if isinstance(retry_exc, (list, tuple)):
            return isinstance(exc, tuple(retry_exc))
        return False

    def get_lineage(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._lock:
            return self._lineage.get(task_id)

    def get_pending_spec(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._lock:
            entry = self._pending.get(task_id)
            if entry is None:
                # the task may be in flight under a retry id
                current = self._pending_origin.get(task_id)
                if current is not None:
                    entry = self._pending.get(current)
            return entry[0] if entry is not None else None

    def evict_lineage(self, task_id: TaskID) -> None:
        with self._lock:
            if self._lineage.pop(task_id, None) is not None:
                self._lineage_bytes -= 256

    def evict_lineage_batch(self, object_ids: List[ObjectID]) -> None:
        """One lock hold for a whole out-of-scope drain."""
        with self._lock:
            pop = self._lineage.pop
            for oid in object_ids:
                if pop(oid.task_id(), None) is not None:
                    self._lineage_bytes -= 256

    def _evict_lineage_locked(self):
        while self._lineage_bytes > self._lineage_cap.value // 2 \
                and self._lineage:
            self._lineage.pop(next(iter(self._lineage)))
            self._lineage_bytes -= 256

    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)


class _Dispatcher:
    """Scheduler -> execution boundary. Callable for one task (every
    scheduler supports this); dispatch_many lets batch-aware schedulers
    hand a whole tick's grants over at once (per-worker message
    coalescing in the process pools)."""

    __slots__ = ("_worker",)

    def __init__(self, worker: "Worker"):
        self._worker = worker

    def __call__(self, pending) -> None:
        plane = self._worker.qos_plane
        if plane is not None:
            plane.note_dispatched(pending.spec.task_id)
        self._worker._dispatch(pending)

    def dispatch_many(self, pendings) -> None:
        plane = self._worker.qos_plane
        if plane is not None:
            for pending in pendings:
                plane.note_dispatched(pending.spec.task_id)
        self._worker._dispatch_many(pendings)


class _WorkQueue:
    """Purpose-built thread-pool for the execution hot path.

    ThreadPoolExecutor pays, per submission, a Future (one Condition
    allocation), a set_result notify, and an unconditional queue notify
    — all discarded by the dispatcher, which never reads the Future.
    This pool is fire-and-forget: no Future, and the wake notify is
    skipped whenever no thread is parked (under load none are)."""

    def __init__(self, nworkers: int, name: str = "ray_tpu_worker"):
        self.num_threads = nworkers
        self._cv = threading.Condition()
        self._q: collections.deque = collections.deque()
        self._idle = 0
        self._stop = False
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"{name}_{i}") for i in range(nworkers)]
        for t in self._threads:
            t.start()
        # ThreadPoolExecutor's non-daemon threads drained the queue at
        # interpreter exit; daemon threads need an explicit atexit drain
        # to keep that guarantee (unregistered by shutdown())
        import atexit
        atexit.register(self._drain_at_exit)

    def _drain_at_exit(self) -> None:
        if not self._stop:
            self.shutdown(wait=True)

    def submit(self, fn, *args) -> None:
        with self._cv:
            if self._stop:
                raise RuntimeError(
                    "cannot schedule new futures after shutdown")
            self._q.append((fn, args))
            if self._idle:
                self._cv.notify()

    def submit_many(self, items) -> None:
        """Enqueue [(fn, args), ...] under ONE lock acquisition."""
        with self._cv:
            if self._stop:
                raise RuntimeError(
                    "cannot schedule new futures after shutdown")
            self._q.extend(items)
            if self._idle:
                self._cv.notify(min(len(items), self._idle))

    def _run(self) -> None:
        cv, q = self._cv, self._q
        while True:
            with cv:
                while not q and not self._stop:
                    self._idle += 1
                    cv.wait()
                    self._idle -= 1
                if not q:
                    return  # stopping and drained
                fn, args = q.popleft()
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001
                logger.exception("executor task failed")

    def shutdown(self, wait: bool = False,
                 cancel_futures: bool = False) -> None:
        with self._cv:
            self._stop = True
            if cancel_futures:
                self._q.clear()
            self._cv.notify_all()
        import atexit
        atexit.unregister(self._drain_at_exit)
        if wait:
            # workers drain the remaining queue before exiting (the run
            # loop only returns once stopped AND empty), so joining them
            # gives ThreadPoolExecutor's shutdown(wait=True) semantics
            me = threading.current_thread()
            for t in self._threads:
                if t is not me:
                    t.join()


class Worker:
    """The in-process runtime: one per driver/worker process."""

    def __init__(self, *, num_cpus: Optional[float] = None,
                 num_workers: Optional[int] = None,
                 scheduler_factory: Optional[Callable] = None,
                 job_id: Optional[JobID] = None,
                 resources: Optional[Dict[str, float]] = None,
                 log_to_driver: bool = True):
        self.job_id = job_id or JobID.from_random()
        self.worker_id = WorkerID.from_random()
        self.alive = True
        self._context = _TaskContext()
        self._driver_task_id = TaskID.of(self.job_id)
        self._task_seq = _Counter()
        # ONE random 8-byte namespace for this worker's task ids; the
        # sequence provides uniqueness within it (an os.urandom syscall
        # per task id was a measurable slice of the submission path)
        self._task_unique = os.urandom(8)

        self.memory_store = MemoryStore()
        self._oos_q: collections.deque = collections.deque()
        # flips when a REMOTE node pool registers: only then can a
        # dying ref have a remote copy worth a per-ref GCS lookup
        self._has_remote_nodes = False
        # one-shot guard for the post-failover lease reconciler (kicked
        # by the first daemon rejoin after a journaled head restart)
        self._failover_reconciler_started = False
        self.reference_counter = ReferenceCounter(self._on_object_out_of_scope)
        # declared before the task manager / scheduler exist: both read
        # it on their hot paths (None = QoS plane off)
        self.qos_plane = None
        self.task_manager = TaskManager(self)

        nworkers = num_workers or GLOBAL_CONFIG.num_workers or os.cpu_count() or 4
        self.num_workers = nworkers
        capacity_cpu = num_cpus if num_cpus is not None else float(nworkers)
        self._pool = _WorkQueue(nworkers)

        # log plane: resolve the session log directory BEFORE any pool
        # can exec a worker — spawners name each child's capture files
        # inside it. A `log_dir` knob that is set but unusable raises
        # (loud by design); the default /tmp path degrades to
        # capture-off with a warning.
        from ray_tpu._private import log_plane
        self.session_log_dir: Optional[str] = None
        if GLOBAL_CONFIG.log_capture:
            try:
                self.session_log_dir = log_plane.resolve_session_log_dir(
                    GLOBAL_CONFIG.log_dir)
            except OSError as e:
                logger.warning("log capture disabled: cannot create "
                               "session log dir (%s)", e)
        log_plane.set_session_log_dir(self.session_log_dir)

        # P3 multi-process node runtime: process workers + shm object store
        # (reference: raylet WorkerPool + plasma). Thread mode keeps the
        # original single-process semantics as the conformance oracle.
        self.shm_store = None
        self.process_pool = None
        if GLOBAL_CONFIG.worker_mode == "process":
            from ray_tpu._private.runtime.process_pool import ProcessWorkerPool
            from ray_tpu._private.runtime.shm_store import ShmObjectStore
            self.shm_store = ShmObjectStore(GLOBAL_CONFIG.object_store_memory)
            self.process_pool = ProcessWorkerPool(self, nworkers,
                                                  self.shm_store)

        # node 0 = "this node"; virtual cluster tests add more. Named
        # custom resources must be DECLARED (init(resources={...})) to be
        # schedulable here — an undeclared name parks tasks as infeasible
        # until a node providing it joins (reference semantics).
        self.node_id = NodeID.from_random()
        head_custom = dict(resources or {})
        # thread mode gets a dispatch window too: the bounded executor
        # (max_workers=n) queues over-dispatched tasks while running at
        # most n concurrently — the same guarantee the process pool's
        # worker pipes give
        from ray_tpu._private.runtime.process_pool import auto_pipeline_depth
        win = (self.process_pool._pipeline_depth
               if self.process_pool is not None
               else auto_pipeline_depth(nworkers))
        node = NodeState((capacity_cpu, _detect_tpu_count(), 1e18,
                          sum(head_custom.values())),
                         node_id=self.node_id,
                         custom_resources=head_custom,
                         window_factor=win)
        contains = self.memory_store.contains
        dispatcher = _Dispatcher(self)
        if scheduler_factory is not None:
            self.scheduler: SchedulerBase = scheduler_factory(
                [node], dispatcher, contains)
        else:
            self.scheduler = EventScheduler([node], dispatcher, contains)

        # control plane (node/actor/job tables, KV, pubsub, health checks)
        from ray_tpu._private.gcs import GcsJournal, GcsService
        journal = None
        if GLOBAL_CONFIG.gcs_journal_path:
            journal = GcsJournal(GLOBAL_CONFIG.gcs_journal_path)
        self.gcs = GcsService(self, journal=journal)
        self.gcs.register_node(
            self.node_id, 0,
            {"CPU": capacity_cpu, "TPU": _detect_tpu_count(),
             **head_custom},
            kind="process" if self.process_pool is not None else "local",
            pool=self.process_pool)
        self.gcs.register_job(self.job_id)
        # per-node worker pools for virtual multi-node clusters
        # (row -> ProcessWorkerPool); node 0's pool is process_pool
        self._node_pools: Dict[int, Any] = {}
        if self.process_pool is not None:
            self._node_pools[0] = self.process_pool
        # TCP registration endpoint for remote node daemons / clients
        # (created lazily with the first remote node)
        self._head_server = None
        self.client_server = None

        # cross-node transfer accounting (tests assert the head's relay
        # stays flat when a direct peer path exists). locality_hit/miss
        # count dispatches whose args were fully/partially resident on
        # the chosen node; bytes_pulled is cross-node staging traffic,
        # bytes_saved is arg bytes already resident where the task ran.
        # mutated from the scheduler tick, daemon demux threads, and
        # head pull paths concurrently — all writers go through
        # note_transfer() under _transfer_stats_lock (raylint
        # shared_state pass: unguarded += across threads drops counts)
        self.transfer_stats: Dict[str, int] = {"head_relayed_bytes": 0,
                                               "head_relayed_objects": 0,
                                               "locality_hits": 0,
                                               "locality_misses": 0,
                                               "bytes_pulled": 0,
                                               "bytes_saved": 0}
        self._transfer_stats_lock = runtime_sanitizer.wrap_lock(
            threading.Lock(), "_private.worker.Worker._transfer_stats_lock")
        # two-level scheduling / p2p actor plane accounting (zeros keep
        # the metric families schema-stable while the knobs are off).
        # Written from daemon demux threads and the head rpc pool at
        # once — same locked-increment contract as transfer_stats.
        self.two_level_stats: Dict[str, int] = {"local_dispatch": 0,
                                                "spillback": 0,
                                                "p2p": 0,
                                                "head_fallback": 0,
                                                "node_deaths": 0,
                                                "orphan_retried": 0,
                                                "orphan_fenced": 0}
        # p2p exactly-once arbiter: first arrival (completion receipt
        # OR head fallback) for a task id claims it, the loser no-ops.
        # Bounded FIFO — duplicates race within seconds, not hours.
        self._p2p_seen: "OrderedDict[bytes, bool]" = OrderedDict()
        self._p2p_seen_lock = runtime_sanitizer.wrap_lock(
            threading.Lock(), "_private.worker.Worker._p2p_seen_lock")
        # arg-object pins for locally-dispatched ref-carrying leases
        # (tid_bin -> [ObjectID]); released when the lease resolves
        self._local_lease_pins: Dict[bytes, List[ObjectID]] = {}
        self._local_pin_lock = runtime_sanitizer.wrap_lock(
            threading.Lock(), "_private.worker.Worker._local_pin_lock")
        # resubmittable bodies of adopted local leases (tid_bin ->
        # journal-shaped record), retained IN MEMORY regardless of the
        # journal knob: the node-death reconciler needs them to retry
        # a dead node's orphaned leases under their original return
        # oids, and the default config journals nothing. Dropped when
        # the lease resolves (same lifetime as the arg pins above).
        self._local_lease_records: Dict[bytes, dict] = {}
        # COMPLETED local leases' records, kept for lineage
        # reconstruction (their returns may be the sole copy in the
        # producing node's arena, and no head-side TaskSpec exists to
        # re-run them) — see release_local_lease_pins(keep_lineage=True)
        self._local_lease_lineage: Dict[bytes, dict] = {}
        # arena names of nodes declared DEAD whose daemon may still
        # re-dial (partition, not death): their rejoin gets a FENCED
        # pool so stale outbox replays from the dead era can never
        # double-resolve work the reconciler already settled
        self._fenced_arenas: Dict[str, float] = {}
        # resource-view push thread (started with the first remote
        # node; sends only while a two-level knob is on)
        self._resview_thread: Optional[threading.Thread] = None
        # resview versioning: v is a monotonic per-push counter; e is a
        # per-head-instance epoch so gossiped views from a dead head's
        # era can never outrank a restarted head's fresh pushes
        self._resview_push_v = 0
        self._resview_epoch = os.urandom(8).hex()
        # single-flight head-side peer pulls (oid -> completion event)
        self._head_pull_lock = runtime_sanitizer.wrap_lock(
            threading.Lock(), "_private.worker.Worker._head_pull_lock")
        self._head_pulls: Dict[ObjectID, threading.Event] = {}

        # placement groups (bundle reservation over the scheduler)
        from ray_tpu._private.placement_groups import PlacementGroupManager
        self.placement_groups = PlacementGroupManager(self)

        # lineage reconstruction for lost objects
        from ray_tpu._private.object_recovery import ObjectRecoveryManager
        self.object_recovery = ObjectRecoveryManager(self)

        # observability: task profile events + optional Prometheus port
        from ray_tpu._private.events import EventBuffer
        self.events = EventBuffer()
        # task event plane: cluster-wide lifecycle records (None when
        # task_events_max=0 — every producer hook is a None check)
        from ray_tpu._private.task_events import TaskEventAggregator
        self.task_events = (TaskEventAggregator()
                            if GLOBAL_CONFIG.task_events_max != 0
                            else None)
        self.scheduler.task_events = self.task_events
        # trace plane: causal spans keyed by trace_id (None when
        # trace_sample_rate=0 or traces_max=0 — specs are never stamped
        # and every producer hook is a None check)
        from ray_tpu._private.trace_plane import TraceAggregator
        self.trace_plane = (TraceAggregator()
                            if (GLOBAL_CONFIG.trace_sample_rate > 0
                                and GLOBAL_CONFIG.traces_max != 0)
                            else None)
        # profile/utilization plane: continuous sampling profiler +
        # per-node resource time series (None when profile_hz=0, the
        # default — no sampler threads anywhere, every producer hook is
        # a None check, metric families render schema-stable zeros)
        self.profile_plane = None
        if GLOBAL_CONFIG.profile_hz > 0:
            from ray_tpu._private.profile_plane import ProfilePlane
            self.profile_plane = ProfilePlane()
            self.profile_plane.start_head_samplers(
                gauges=self._head_util_gauges())
        # locality column input: the scheduler reads copy locations
        # straight off the GCS object directory (primary first)
        self.scheduler.locations_of = self.gcs.object_locations
        self.metrics_server = None
        if GLOBAL_CONFIG.metrics_export_port:
            from ray_tpu._private.metrics import MetricsServer
            try:
                self.metrics_server = MetricsServer(
                    self, GLOBAL_CONFIG.metrics_export_port)
            except OSError as e:
                # a port conflict degrades to metrics-disabled; it must
                # not fail init and leak the already-started runtime
                logger.warning("metrics endpoint disabled: cannot bind "
                               "port %d (%s)",
                               GLOBAL_CONFIG.metrics_export_port, e)

        # log plane: announce the session dir in the GCS KV (clients /
        # tools discover it there), mirror control-plane log records
        # into logs/gcs.out, and start the driver-streaming monitor
        self.log_to_driver = log_to_driver
        self.log_monitor = None
        self._gcs_log_handler = None
        if self.session_log_dir is not None:
            self.gcs.kv_put(b"session_log_dir",
                            self.session_log_dir.encode(),
                            namespace="session")
            import logging as _logging
            try:
                h = _logging.FileHandler(
                    os.path.join(self.session_log_dir, "gcs.out"),
                    delay=True)
                h.setFormatter(_logging.Formatter(
                    "%(asctime)s %(levelname)s %(name)s: %(message)s"))
                h.setLevel(_logging.INFO)
                _logging.getLogger("ray_tpu").addHandler(h)
                self._gcs_log_handler = h
            except OSError:
                pass
            if log_to_driver:
                from ray_tpu._private.log_monitor import LogMonitor
                self.log_monitor = LogMonitor(self, self.session_log_dir)

        # actors: ActorID -> _ActorRuntime (see actor.py)
        self.actors: Dict[ActorID, Any] = {}
        self.dead_actors: set = set()
        self._actors_lock = runtime_sanitizer.wrap_lock(
            threading.Lock(), "_private.worker.Worker._actors_lock")

        # id -> False (running) | True (cancelled) | "timeout" (the
        # deadline watcher failed this attempt; its results are zombie)
        self._running_tasks: Dict[TaskID, Any] = {}
        # cancelled while window-leased but not yet executing (queued in
        # the executor): flagged here, honored at execution start
        self._precancelled: set = set()
        # deadline expired while executor-queued: timed out at exec start
        self._pretimeout: set = set()
        self._running_lock = runtime_sanitizer.wrap_lock(
            threading.Lock(), "_private.worker.Worker._running_lock")
        if runtime_sanitizer._ENABLED:
            # leak-ledger attribution: the task context current at each
            # shm allocation (the id the task-event plane records under)
            runtime_sanitizer.set_owner_provider(
                lambda: f"task {self.current_task_id.hex()[:16]}")

        # chaos plane: every injection decision flows through the
        # process-wide seeded controller (see _private/chaos.py)
        self._chaos = _chaos_controller()
        self._tick_delay_entry = GLOBAL_CONFIG.entry("testing_tick_delay_s")
        # per-task deadlines (spec.timeout_s): a lazily-started watcher
        # cancels attempts past their deadline; each expiry counts
        # against max_retries and surfaces TaskTimeoutError
        self._deadline_cv = threading.Condition()
        self._deadline_heap: List[tuple] = []
        self._deadline_seq = _Counter()
        self._deadline_thread: Optional[threading.Thread] = None

        # QoS plane (config.qos, declared early in __init__): tenant
        # fair-share ordering at the head, starvation-triggered
        # preemption, and the top-spilled-tier watermark on resview
        # frames. Stays None when the knob is off — every QoS hook is a
        # `plane is not None` check, so the off state stays
        # byte-for-byte pre-QoS.
        self._qos_thread: Optional[threading.Thread] = None
        if GLOBAL_CONFIG.qos:
            from ray_tpu._private.qos import QosPlane
            self.qos_plane = QosPlane(
                tenant_quotas=GLOBAL_CONFIG.tenant_quotas,
                preempt_grace_s=GLOBAL_CONFIG.preempt_grace_s)
            self.scheduler.qos_plane = self.qos_plane
            self._qos_thread = threading.Thread(
                target=self._qos_loop, daemon=True, name="ray_tpu_qos")
            self._qos_thread.start()

        # deferred unref queue: ObjectRef.__del__ may fire during GC while
        # runtime locks are held, so deletions drain on a dedicated thread
        self._unref_queue: collections.deque = collections.deque()
        self._unref_event = threading.Event()
        self._unref_thread = threading.Thread(
            target=self._unref_loop, daemon=True, name="ray_tpu_unref")
        self._unref_thread.start()

        # memory monitor LAST: its thread scans worker state
        # (_running_tasks, _node_pools) that must exist before the first
        # tick can fire
        from ray_tpu._private.memory_monitor import MemoryMonitor
        self.memory_monitor = MemoryMonitor(self)

    # ------------------------------------------------------------------
    # Context helpers
    # ------------------------------------------------------------------
    @property
    def needs_serialized_funcs(self) -> bool:
        """True when tasks may cross a process boundary, so
        RemoteFunction should attach its cached pickled-function blob
        to specs (thread-only mode skips the pickle entirely)."""
        return self.process_pool is not None or bool(self._node_pools)

    @property
    def current_task_id(self) -> TaskID:
        return self._context.task_id or self._driver_task_id

    def next_task_id(self) -> TaskID:
        return TaskID.of(self.job_id, unique=self._task_unique,
                         seq=self._task_seq.next())

    # -- cluster KV (same surface as ClientWorker.kv_*, so code using
    # `w.kv_put(...)` works in both driver and client mode) -----------
    def kv_get(self, key: bytes, namespace: str = ""):
        return self.gcs.kv_get(key, namespace=namespace)

    def kv_put(self, key: bytes, value: bytes, namespace: str = "") -> None:
        self.gcs.kv_put(key, value, namespace=namespace)

    def kv_del(self, key: bytes, namespace: str = "") -> bool:
        return self.gcs.kv_del(key, namespace=namespace)

    def kv_keys(self, prefix: bytes = b"", namespace: str = ""):
        return self.gcs.kv_keys(prefix, namespace=namespace)

    def next_put_id(self) -> ObjectID:
        self._context.put_counter += 1
        return ObjectID.for_put(self.current_task_id, self._context.put_counter)

    # ------------------------------------------------------------------
    # Object plane: put / get / wait
    # ------------------------------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        self._drain_out_of_scope()
        if isinstance(value, ObjectRef):
            raise TypeError(
                "Calling put() on an ObjectRef is not allowed: the ref can be "
                "passed around directly (reference semantics).")
        object_id = self.next_put_id()
        self.reference_counter.add_owned_object(object_id)
        if self.shm_store is not None and _likely_large(value):
            # large puts go straight to the shm arena (plasma path) so
            # worker processes read them zero-copy; the driver resolves
            # the placeholder lazily on first get
            from ray_tpu._private.object_store import ObjectStoreFullError
            from ray_tpu._private.runtime.process_pool import _PLACEHOLDER
            from ray_tpu._private.serialization import serialize
            sobj = serialize(value)
            if sobj.framed_nbytes() > GLOBAL_CONFIG.inline_object_max_bytes:
                try:
                    # a full arena evicts/spills internally; only a DISK
                    # failure can surface here
                    self.shm_store.put_serialized(object_id, sobj)
                    self.memory_store.put(object_id, _PLACEHOLDER)
                    return ObjectRef(object_id, self.worker_id)
                except (ObjectStoreFullError, OSError) as e:
                    logger.warning(
                        "shm store rejected %d-byte object (%s); storing "
                        "in the host memory store",
                        sobj.framed_nbytes(), e)
        self.memory_store.put(object_id, value)
        return ObjectRef(object_id, self.worker_id)

    def _entry_value(self, object_id: ObjectID, entry) -> Any:
        """Resolve a memory-store entry, deserializing shm-resident bytes
        zero-copy on first access (plasma client get analog); objects
        resident in a REMOTE node's arena fetch over the node link on
        first head-side access (PullManager analog)."""
        from ray_tpu._private.runtime.process_pool import (RemotePlaceholder,
                                                           ShmPlaceholder)
        value = entry.value
        if isinstance(value, ShmPlaceholder):
            from ray_tpu._private.serialization import (
                deserialize, deserialize_with_release)
            sobj, pinned = self.shm_store.get_serialized_for_view(object_id)
            if sobj is None:
                raise rex.ObjectLostError(object_id.hex())
            if pinned:
                # the arena range stays pinned until the LAST view that
                # aliases it (incl. later-taken sub-views) is collected;
                # the helper owns the release even on deserialize errors
                value = deserialize_with_release(
                    sobj,
                    lambda oid=object_id: self.shm_store.unpin(oid))
            else:
                value = deserialize(sobj)  # spill read: copied bytes
            entry.value = value  # memoize the zero-copy view object
        elif isinstance(value, RemotePlaceholder):
            from ray_tpu._private.serialization import (SerializedObject,
                                                        deserialize)
            node_index = value.node_index
            value = self._pull_remote_value(object_id, node_index)
            if value is None:
                # control-channel blob fetch (daemons without a peer
                # plane / pull failure)
                data = self.fetch_object_bytes(object_id, node_index)
                if data is None:
                    raise rex.ObjectLostError(object_id.hex())
                value = deserialize(SerializedObject.from_bytes(data))
            entry.value = value  # memoize: later reads are local
        return value

    def _pull_remote_value(self, object_id: ObjectID,
                           node_index: int) -> Optional[Any]:
        """Chunked peer pull of a remote-resident object into the
        HEAD's own store, then a local read — a multi-GB result never
        rides the daemon CONTROL link as one message (that link also
        carries dispatch and pings). None = peer plane unavailable;
        the caller falls back to the control-channel blob fetch."""
        peer = self.peer_address_of(node_index)
        if peer is None or self._head_server is None:
            return None
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu._private.runtime.node_daemon import peer_pull_bytes
        from ray_tpu._private.serialization import (SerializedObject,
                                                    deserialize)

        timeout = GLOBAL_CONFIG.object_transfer_timeout_s
        authkey = self._head_server.authkey
        if self.shm_store is not None:
            # single-flight per object: two user threads racing the
            # same pull would both begin_adopt — in the spill case onto
            # the SAME temp file (one pid), interleaving writes into a
            # corrupted object
            with self._head_pull_lock:
                ev = self._head_pulls.get(object_id)
                if ev is None:
                    self._head_pulls[object_id] = ev = threading.Event()
                    leader = True
                else:
                    leader = False
            if not leader:
                ev.wait(timeout)
                if not self.shm_store.contains(object_id):
                    return None  # leader failed: fall back
                return self._read_pulled(object_id)
            try:
                return self._leader_pull(peer, authkey, object_id,
                                         timeout)
            finally:
                with self._head_pull_lock:
                    self._head_pulls.pop(object_id, None)
                ev.set()
        # thread-mode head: no arena to adopt into — stream the frames
        # into ONE buffer and deserialize from it (no shared sink, so
        # no single-flight needed beyond memoization upstream)
        buf = peer_pull_bytes(peer, authkey, object_id, timeout)
        if buf is None:
            return None
        self.note_transfer("head_peer_pulled_objects")
        return deserialize(SerializedObject.from_bytes(memoryview(buf)))

    def _leader_pull(self, peer, authkey: bytes, object_id: ObjectID,
                     timeout: float) -> Optional[Any]:
        from ray_tpu._private.runtime.node_daemon import peer_pull_once

        if not peer_pull_once(peer, authkey, self.shm_store, object_id,
                              timeout):
            return None
        self.note_transfer("head_peer_pulled_objects")
        return self._read_pulled(object_id)

    def _read_pulled(self, object_id: ObjectID) -> Optional[Any]:
        from ray_tpu._private.serialization import (
            deserialize, deserialize_with_release)

        sobj, pinned = self.shm_store.get_serialized_for_view(object_id)
        if sobj is None:
            return None
        if pinned:
            return deserialize_with_release(
                sobj, lambda oid=object_id: self.shm_store.unpin(oid))
        return deserialize(sobj)  # spill read: copied bytes

    def fetch_object_bytes(self, object_id: ObjectID,
                           node_index: int) -> Optional[bytes]:
        """Framed bytes of an object primary-resident on a remote node
        (None if the node or object is gone). Every byte returned here
        crossed the HEAD's link — the relay counter lets tests assert
        that peer-capable transfers bypass it."""
        pool = self._node_pools.get(node_index)
        if pool is None or not getattr(pool, "is_remote", False):
            return None
        data = pool.fetch_object(object_id)
        if data is not None:
            from ray_tpu._private.serialization import SerializedObject
            if not SerializedObject.frame_complete(data):
                # partial transfer (chaos truncation or a dying daemon):
                # treat as lost so lineage recovery rebuilds the object
                # instead of deserializing short buffers into garbage
                logger.warning("truncated transfer of %s from node %d "
                               "(%d bytes); treating as lost",
                               object_id.hex()[:16], node_index, len(data))
                self._chaos.note_recovery("transfer")
                return None
            self.note_transfer("head_relayed_bytes", len(data))
            self.note_transfer("head_relayed_objects")
        return data

    def note_transfer(self, key: str, delta: int = 1) -> None:
        """Bump a transfer_stats counter. The dict is written from the
        scheduler tick, daemon demux threads, and head pull paths at
        once; a bare ``+=`` there is a read-modify-write race that
        silently drops counts."""
        with self._transfer_stats_lock:
            self.transfer_stats[key] = \
                self.transfer_stats.get(key, 0) + delta

    def peer_address_of(self, node_index: int) -> Optional[tuple]:
        """The direct-transfer endpoint of a remote node's daemon, or
        None (head-local nodes / daemons predating the peer plane)."""
        pool = self._node_pools.get(node_index)
        if pool is not None and getattr(pool, "is_remote", False):
            return getattr(pool, "peer_address", None)
        return None

    # ------------------------------------------------------------------
    # Two-level scheduling + p2p actor plane (bottom-up dispatch: the
    # node daemons admit work and execute actor calls peer-to-peer; the
    # head stays the single placement/bookkeeping authority and only
    # sees sequenced reports)
    # ------------------------------------------------------------------
    def note_two_level(self, key: str, delta: int = 1) -> None:
        with self._transfer_stats_lock:
            self.two_level_stats[key] = \
                self.two_level_stats.get(key, 0) + delta

    def _p2p_claim(self, tid_bin: bytes) -> bool:
        """First claim on a p2p call's completion wins: the completion
        receipt and the head-fallback retry race for the same task id,
        and exactly one of them may resolve/execute it."""
        with self._p2p_seen_lock:
            if tid_bin in self._p2p_seen:
                return False
            self._p2p_seen[tid_bin] = True
            while len(self._p2p_seen) > 4096:
                self._p2p_seen.popitem(last=False)
            return True

    def on_local_lease(self, pool, tid_bin: bytes, info: dict) -> None:
        """A node's LocalScheduler admitted a worker-submitted task
        from its bounded local queue without a head round-trip. Adopt
        the lease head-side: own + journal it so failover
        reconciliation and ref bookkeeping behave exactly as if the
        head had placed it (outbox FIFO guarantees this report lands
        before the lease's own done/err)."""
        self.note_two_level("local_dispatch")
        note = getattr(self.scheduler, "note_local_dispatch", None)
        if note is not None:
            note()
        returns = list(info.get("returns") or ())
        rids = [ObjectID(b) for b in returns]
        for oid in rids:
            self.reference_counter.add_owned_object(oid)
        with pool._lock:
            h = pool._by_num.get(info.get("worker_num"))
            sub = pool._by_num.get(info.get("submitter"))
        attempt = int(info.get("attempt", 0))
        if h is not None:
            pool.adopt_inflight(h, tid_bin, returns, attempt)
        record = {
            "name": info.get("name"),
            "fn_blob": info.get("fn_blob"),
            "args_blob": info.get("args_blob"),
            "num_returns": int(info.get("num_returns", 1)),
            "returns": returns,
            "resources": dict(info.get("resources") or {}),
            "attempt": attempt,
            "max_retries": int(info.get("max_retries", 0)),
            "node_index": pool.node_index,
        }
        # retained in memory for the node-death reconciler even when
        # the durable journal is off (the default): a whole-node
        # SIGKILL must be able to retry this lease under its original
        # return oids without any WAL to replay
        with self._local_pin_lock:
            self._local_lease_records[tid_bin] = record
        if self.gcs.journal_enabled:
            self.gcs.journal_lease(tid_bin, dict(record))
        arg_pin = [ObjectID(b) for b in info.get("arg_refs") or ()]
        if arg_pin:
            # pin the arg objects for the lease's lifetime, mirroring
            # the head path's submitted-task references (released when
            # the adopted lease resolves — see release_local_lease_pins)
            self.reference_counter.add_submitted_task_references(arg_pin)
            with self._local_pin_lock:
                self._local_lease_pins[tid_bin] = arg_pin
        if sub is not None:
            # the submitting task borrows its nested refs until it
            # completes, mirroring the head-path _rpc_submit
            borrows = pool._task_borrows(sub)
            for oid in rids:
                self.reference_counter.add_borrower(oid, sub.worker_id)
                borrows.add(oid)
        tp = self.trace_plane
        if tp is not None:
            ts = info.get("t")
            tp.record_local_dispatch(
                TaskID(tid_bin), info.get("name") or "?",
                info.get("trace"), pool.node_index,
                now=(ts + pool.clock_offset) if ts else None)

    def on_local_retry(self, pool, tid_bin: bytes, info: dict) -> None:
        """The node daemon re-leased a locally-dispatched task to a
        fresh local worker after its first worker died (per-attempt
        accounting rides the journaled lease, so failover replay and
        the real claimant agree on who owns the attempt). Move the
        inflight entry off the dead handle and bump the journal's
        attempt token — outbox FIFO guarantees this report lands
        before the dead worker's worker_died, so the failure sweep
        never sees the retried lease on the old handle."""
        self.note_two_level("local_retry")
        attempt = int(info.get("attempt", 1))
        returns = list(info.get("returns") or ())
        task_id = TaskID(tid_bin)
        with pool._lock:
            old = pool._by_task.pop(task_id, None)
            h = pool._by_num.get(info.get("worker_num"))
            if old is not None:
                old.inflight.pop(task_id, None)
        if h is not None:
            pool.adopt_inflight(h, tid_bin, returns, attempt)
        with self._local_pin_lock:
            rec = self._local_lease_records.get(tid_bin)
            if rec is not None:
                rec["attempt"] = attempt
        if self.gcs.journal_enabled:
            lease = self.gcs.journal_get(tid_bin)
            if lease is not None:
                lease = dict(lease, attempt=attempt)
                self.gcs.journal_lease(tid_bin, lease)
        tp = self.trace_plane
        if tp is not None:
            tp.record_failed(TaskID(tid_bin),
                             "worker died (local retry %d)" % attempt)

    def release_local_lease_pins(self, tid_bin: bytes,
                                 keep_lineage: bool = False) -> None:
        """Drop the arg-object pins taken at local-lease adoption,
        plus the retained resubmittable record (the lease reached a
        terminal state on every path that calls this). No-op for tasks
        without pinned args (head-path tasks, failover re-attached
        leases).

        ``keep_lineage`` (the SUCCESS completion path): the head never
        built a TaskSpec for a locally-dispatched lease, so the lease
        record is the ONLY thing that can reconstruct its sole-copy
        returns after the producing node dies. Migrate it to the
        bounded lineage-record table instead of dropping it; the
        recovery manager resubmits through it on loss."""
        with self._local_pin_lock:
            pins = self._local_lease_pins.pop(tid_bin, None)
            rec = self._local_lease_records.pop(tid_bin, None)
            if keep_lineage and rec is not None \
                    and rec.get("fn_blob") is not None \
                    and int(rec.get("attempt", 0)) \
                    < int(rec.get("max_retries", 0)):
                lt = self._local_lease_lineage
                lt[tid_bin] = rec
                # count-capped FIFO (records carry real fn/args blobs,
                # unlike the 256-byte-estimated head-path specs);
                # evicted entries are simply no longer recoverable
                while len(lt) > 2048:
                    lt.pop(next(iter(lt)))
        if pins:
            self.reference_counter.remove_submitted_task_references(pins)

    def take_local_lease_lineage(self, tid_bin: bytes) -> Optional[dict]:
        """Claim (pop) a completed local lease's lineage record for
        reconstruction. Popping is the dedup: once the resubmission
        completes, the rebuilt spec lands in the task manager's normal
        lineage table (keyed by this same original id), and further
        losses recover through that path."""
        with self._local_pin_lock:
            return self._local_lease_lineage.pop(tid_bin, None)

    def on_p2p_done(self, pool, tid_bin: bytes, receipt: dict) -> None:
        """Sequenced completion receipt for a peer-to-peer actor call:
        the result bytes already moved worker -> peer daemon directly,
        so this is lineage, ownership and observability only.
        ``pool`` is the EXECUTING node's pool (its daemon reported)."""
        if not self._p2p_claim(tid_bin):
            return  # the head-fallback retry already resolved the call
        self.note_two_level("p2p")
        returns = list(receipt.get("returns") or ())
        rids = [ObjectID(b) for b in returns]
        for oid in rids:
            self.reference_counter.add_owned_object(oid)
        err = receipt.get("err")
        if err is not None:
            import cloudpickle
            try:
                exc = cloudpickle.loads(err[0])
            except Exception:
                exc = RuntimeError(
                    "p2p actor call failed (exception undeserializable)")
            if not isinstance(exc, (rex.TaskError, rex.ActorError)):
                exc = rex.TaskError(
                    f"{receipt.get('name')}.{receipt.get('method')}",
                    exc, err[1] or "")
            for oid in rids:
                self.memory_store.put(oid, exc, is_exception=True)
                self.scheduler.notify_object_ready(oid)
        else:
            pool.store_result_entries(rids,
                                      list(receipt.get("entries") or ()))
        # the calling task (on the CALLER's node) borrows the refs
        # until it completes, mirroring the head-path _rpc_actor_call
        cpool = self._node_pools.get(receipt.get("caller_node"))
        if cpool is not None:
            with cpool._lock:
                ch = cpool._by_num.get(receipt.get("caller"))
            if ch is not None:
                borrows = cpool._task_borrows(ch)
                for oid in rids:
                    self.reference_counter.add_borrower(oid, ch.worker_id)
                    borrows.add(oid)
        tp = self.trace_plane
        if tp is not None:
            tp.record_p2p_span(
                TaskID(tid_bin),
                f"{receipt.get('name')}.{receipt.get('method')}",
                receipt.get("trace"), pool.node_index,
                receipt.get("timing"),
                worker=receipt.get("worker_num"),
                offset=pool.clock_offset,
                error_type=(type(exc).__name__ if err is not None
                            else None))

    def on_p2p_fallback(self, pool, tid_bin: bytes, info: dict) -> None:
        """A peer lane died/dropped/timed out mid-call: re-execute
        through the normal head-side actor runtime with the SAME task
        id / return ids / trace context. The executing worker's dedup
        cache re-emits the recorded completion if the peer actually
        ran the first attempt — exactly-once either way. ``pool`` is
        the CALLER's pool (its daemon reported the fallback)."""
        import cloudpickle

        from ray_tpu.actor import ActorState, _Call

        if not self._p2p_claim(tid_bin):
            return  # the completion receipt beat the fallback report
        # count only claimed fallbacks (mirrors on_p2p_done's 'p2p'
        # accounting) — a lost race here was a fully-served p2p call
        self.note_two_level("head_fallback")
        self._chaos.note_recovery("peer_link")
        returns = list(info.get("returns") or ())
        rids = [ObjectID(b) for b in returns]
        for oid in rids:
            self.reference_counter.add_owned_object(oid)

        def _fail(exc: BaseException) -> None:
            for oid in rids:
                self.memory_store.put(oid, exc, is_exception=True)
                self.scheduler.notify_object_ready(oid)

        try:
            t = cloudpickle.loads(info["blob"])
            args, kwargs = t[2], t[3]
        except Exception as e:
            _fail(rex.TaskError(str(info.get("method")), e, ""))
            return
        aid = ActorID(info["actor"])
        with self._actors_lock:
            rt = self.actors.get(aid)
        if rt is None or rt.state == ActorState.DEAD:
            _fail(rex.ActorDiedError(
                f"p2p fallback: actor {aid.hex()[:16]} is gone "
                f"({info.get('reason')})", actor_id=aid))
            return
        call = _Call(info["method"], args, kwargs, rids,
                     int(info.get("num_returns", 1)), TaskID(tid_bin),
                     trace_ctx=info.get("trace"), dedup=True)
        tp = self.trace_plane
        if tp is not None and call.trace_ctx is not None:
            tp.on_actor_call(call, str(info.get("method")),
                             rt._current_node_index)
        with pool._lock:
            ch = pool._by_num.get(info.get("caller"))
        if ch is not None:
            borrows = pool._task_borrows(ch)
            for oid in rids:
                self.reference_counter.add_borrower(oid, ch.worker_id)
                borrows.add(oid)
        try:
            rt.submit(call)
        except Exception as e:  # e.g. PendingCallsLimitExceeded
            _fail(e if isinstance(e, rex.RayTpuError)
                  else rex.TaskError(str(info.get("method")), e, ""))

    def resolve_actor_address(self, aid_bin: bytes) -> Optional[tuple]:
        """(node_index, peer_address, worker_num) of a live process
        actor's dedicated worker, or None (thread-mode actor, not
        alive, or node without a peer plane) — a None route keeps the
        daemon on the head path. Knob-gated: with actor_p2p off no
        route exists anywhere (``state.list_actors`` shows None and
        aroute requests — which should not occur — resolve to the
        head path)."""
        from ray_tpu.actor import ActorState

        if not GLOBAL_CONFIG.actor_p2p:
            return None
        with self._actors_lock:
            rt = self.actors.get(ActorID(aid_bin))
        if rt is None or rt.state != ActorState.ALIVE:
            return None
        h = getattr(rt, "_h", None)
        rpool = getattr(rt, "_pool", None)
        if h is None or rpool is None or h.dead \
                or not getattr(rpool, "is_remote", False):
            return None
        peer = getattr(rpool, "peer_address", None)
        if peer is None:
            return None
        return (rpool.node_index, tuple(peer), h.worker_num)

    def _ensure_resview_push(self) -> None:
        """Start the resource-view push loop with the first remote
        node. The loop itself is knob-gated per tick, so toggling
        local_dispatch/actor_p2p mid-session takes effect without a
        restart; with both knobs off it sends NOTHING (wire bytes stay
        byte-for-byte pre-two-level)."""
        if self._resview_thread is not None:
            return
        t = threading.Thread(target=self._resview_push_loop, daemon=True,
                             name="ray_tpu_resview_push")
        self._resview_thread = t
        t.start()

    # residency digests above this size stop being pushed (a node
    # hoarding tens of thousands of objects gains little from local
    # ref admission and the push would dominate the view payload)
    _RESVIEW_DIGEST_CAP = 4096

    def _resview_push_loop(self) -> None:
        while self.alive:
            try:
                if GLOBAL_CONFIG.local_dispatch or GLOBAL_CONFIG.actor_p2p:
                    snap = self._chaos.plan_snapshot()
                    self._resview_push_v += 1
                    pools = [e.pool for e in self.gcs.node_table()
                             if e.pool is not None
                             and getattr(e.pool, "is_remote", False)]
                    addrs = {p.node_index: getattr(p, "peer_address", None)
                             for p in pools}
                    # per-node top-spilled-tier watermark (config.qos):
                    # the highest priority tier still queued at the
                    # head — daemons must not locally admit below it
                    # (a low-tier nested task would jump a spilled
                    # high-tier one). The key is absent entirely when
                    # the plane is off: qos=False frames stay
                    # byte-for-byte pre-QoS.
                    wm = (self.qos_plane.top_queued_tier()
                          if self.qos_plane is not None else None)
                    for p in pools:
                        try:
                            view = {
                                "accept": bool(GLOBAL_CONFIG.local_dispatch),
                                "p2p": bool(GLOBAL_CONFIG.actor_p2p),
                                "cap": int(GLOBAL_CONFIG.local_queue_depth),
                                "job": self.job_id.binary(),
                                "node": p.node_index,
                                "chaos": snap,
                                "v": self._resview_push_v,
                                "e": self._resview_epoch,
                                "peers": [a for i, a in addrs.items()
                                          if i != p.node_index
                                          and a is not None],
                                "resident": self._residency_digest(
                                    p.node_index),
                            }
                            if self.qos_plane is not None:
                                view["wm"] = wm
                            p.send_resview(view)
                        except Exception:
                            pass  # a dying link re-syncs after rejoin
            except Exception:
                logger.exception("resview push tick failed")
            time.sleep(0.5)

    def _residency_digest(self, node_index: int) -> Optional[list]:
        """8-byte oid prefixes of every object copy on the node, for
        the LocalScheduler's ref-carrying admission check. None when
        the directory slice is too large to ship (the daemon then
        falls back to its own arena residency, which it always checks
        first anyway)."""
        oids = self.gcs.objects_resident(node_index)
        if len(oids) > self._RESVIEW_DIGEST_CAP:
            return None
        return [oid.binary()[:8] for oid in oids]

    def _head_util_gauges(self) -> dict:
        """Internal gauges the head's resource sampler folds into node
        0's utilization series: shm arena occupancy, scheduler queue
        depths, inflight leases, control-ring traffic. Closures are
        evaluated once per utilization_interval_s tick, so the cheap
        locked reads below never touch a hot path."""
        def _arena_used() -> int:
            arena = getattr(getattr(self, "shm_store", None), "arena",
                            None)
            if arena is None:
                return 0
            return max(arena.size - arena.free_bytes(), 0)

        def _sched(key: str):
            def g():
                return self.scheduler.stats().get(key, 0)
            return g

        def _ring(key: str):
            def g():
                total = 0
                for e in self.gcs.node_table():
                    rs = getattr(e.pool, "ring_stats", None)
                    if rs:
                        total += rs.get(key, 0)
                return total
            return g

        return {
            "arena_used_bytes": _arena_used,
            "sched_ready_queue": _sched("ready_queue"),
            "sched_waiting_deps": _sched("waiting_deps"),
            "inflight_tasks": _sched("running"),
            "ring_msgs_total": _ring("msgs"),
            "ring_fallback_total": _ring("fallback"),
            "head_failovers": lambda: getattr(self.gcs,
                                              "head_failovers", 0),
        }

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float]) -> List[Any]:
        self._drain_out_of_scope()
        ids = [r.object_id() for r in refs]
        # lost objects (freed/evicted while still referenced) reconstruct
        # from lineage before we block on the store
        missing = self.memory_store.missing_of(ids)
        if missing:
            self._check_env_lock_deadlock(missing)
            self.object_recovery.recover_all(missing)
        try:
            entries = self.memory_store.wait_and_get(ids, timeout)
        except TimeoutError as e:
            raise rex.GetTimeoutError(str(e)) from None
        out = []
        for oid, entry in zip(ids, entries):
            if entry.is_exception:
                exc = entry.value
                if isinstance(exc, rex.TaskError):
                    raise exc.as_instanceof_cause()
                raise exc
            out.append(self._entry_value(oid, entry))
        return out

    def _env_lock_blocked_specs(self, missing: List[ObjectID]) -> List[TaskSpec]:
        """Pending producers of `missing` that need the thread-mode
        runtime-env lock, when the CALLING thread holds it — those
        tasks can never run until the caller finishes (thread workers
        serialize env'd tasks under one lock)."""
        if Worker._env_lock_owner != threading.get_ident():
            return []
        blocked = []
        for oid in missing:
            spec = self.task_manager.pending_spec_for_object(oid)
            env = spec.runtime_env if spec is not None else None
            if env and (env.get("working_dir_pkg") or env.get("pip")):
                if self._spec_fits_process_pool(spec):
                    # mixed topology: a process-backed node can satisfy
                    # this producer's demands, and its workers apply
                    # runtime envs WITHOUT the thread-mode lock — the
                    # task is not necessarily stuck behind the caller,
                    # so flagging it would be a spurious deadlock error
                    continue
                blocked.append(spec)
        return blocked

    def _spec_fits_process_pool(self, spec: TaskSpec) -> bool:
        """True when some process-backed node's declared resources cover
        the spec's demands (i.e. the scheduler CAN run it off the local
        thread pool). Heuristic on purpose: the grant may still land on
        local threads, but erring toward not-raising beats failing a
        program that can make progress."""
        if not self._node_pools:
            return False
        demands = dict(spec.resources or {})
        demands.setdefault("CPU", 0.0)
        for entry in self.gcs.node_table():
            if entry.pool is None or entry.kind == "local":
                continue
            caps = entry.resources
            if all(caps.get(k, 0.0) >= v for k, v in demands.items()):
                return True
        return False

    def _check_env_lock_deadlock(self, missing: List[ObjectID]) -> None:
        """Fail loudly where a thread-mode env'd task would deadlock
        blocking on another env'd task (fire-and-forget nested env'd
        tasks remain legal — they run after the blocker releases)."""
        blocked = self._env_lock_blocked_specs(missing)
        if blocked:
            raise RuntimeError(
                f"deadlock: task {blocked[0].name} needs the "
                "thread-mode runtime-env lock held by the task blocking "
                "on it (thread workers serialize env'd tasks). Use "
                "process workers for nested runtime environments, or "
                "don't block on env'd children from an env'd task.")

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        self._drain_out_of_scope()
        ids = [r.object_id() for r in refs]
        if timeout is None:
            # deadlock only if the wait CANNOT be satisfied without an
            # env-lock-blocked producer: refs already ready or produced
            # by plain tasks still count toward num_returns
            missing = [oid for oid in ids
                       if not self.memory_store.contains(oid)]
            blocked = self._env_lock_blocked_specs(missing)
            if blocked and len(ids) - len(blocked) < num_returns:
                raise RuntimeError(
                    f"deadlock: wait(num_returns={num_returns}) cannot "
                    f"complete without task {blocked[0].name}, which "
                    "needs the thread-mode runtime-env lock held by the "
                    "waiting task. Use process workers for nested "
                    "runtime environments.")
        ready_set = self.memory_store.wait(ids, num_returns, timeout)
        ready, not_ready = [], []
        for r in refs:
            (ready if r.object_id() in ready_set and len(ready) < num_returns
             else not_ready).append(r)
        return ready, not_ready

    def run_callback_when_ready(self, object_id: ObjectID, cb: Callable[[], None]):
        self.memory_store.add_ready_callback(object_id, cb)

    # ------------------------------------------------------------------
    # Task submission
    # ------------------------------------------------------------------
    def prepare_runtime_env(self, runtime_env: Optional[dict]
                            ) -> Optional[dict]:
        """Driver-side half of the env agent: package working_dir into
        a content-addressed zip in the GCS KV (once per content), so
        every node can fetch it on demand. Returns the env with the
        path replaced by its package hash."""
        if not runtime_env or "working_dir" not in runtime_env:
            return runtime_env
        from ray_tpu._private import runtime_envs as rte

        pkg_hash, data = rte.package_working_dir(runtime_env["working_dir"])
        key = rte.kv_key(pkg_hash)
        if self.gcs.kv_get(key) is None:
            self.gcs.kv_put(key, data)
        out = dict(runtime_env)
        out.pop("working_dir")
        out["working_dir_pkg"] = pkg_hash
        return out

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        if spec.runtime_env and "working_dir" in spec.runtime_env:
            spec.runtime_env = self.prepare_runtime_env(spec.runtime_env)
        return_ids = spec.return_ids()
        for oid in return_ids:
            self.reference_counter.add_owned_object(oid, lineage_task=spec.task_id)

        deps = _top_level_deps(spec.args, spec.kwargs)
        spec._deps_memo = deps  # args never change; reused at completion
        if deps:
            self.reference_counter.add_submitted_task_references(deps)
            self._stamp_arg_sizes(spec, deps)
        self.task_manager.add_pending(spec, deps)
        if self.qos_plane is not None:
            self.qos_plane.note_queued(spec.task_id, spec.tenant,
                                       spec.priority)
        self.events.record(spec.task_id, spec.name, "submitted",
                           attempt=spec.attempt_number)
        # trace stamping runs BEFORE the task-event record so the
        # event plane's detail rows can carry the spec's trace context
        if (self.trace_plane is not None
                and spec.task_type == TaskType.NORMAL_TASK):
            self.trace_plane.on_submit(spec)
        if (self.task_events is not None
                and spec.task_type == TaskType.NORMAL_TASK):
            self.task_events.record_submitted(spec)
        if spec.timeout_s:
            self._register_deadline(spec)

        # drop deps already available locally; a missing dep with no
        # pending producer was LOST and must reconstruct or the task
        # waits forever
        unresolved = []
        for d in deps:
            if self.memory_store.contains(d):
                continue
            unresolved.append(d)
            self.object_recovery.maybe_recover(d)
        pending = PendingTask(spec=spec, deps=unresolved, execute=_noop_exec)
        self.scheduler.submit(pending)
        return [ObjectRef(oid, self.worker_id) for oid in return_ids]

    def submit_task_batch(self, specs: List[TaskSpec]) -> List[List[ObjectRef]]:
        """Vectorized submit: per-task work hoisted to per-batch — one
        refcount lock hold, one task-manager lock hold, one scheduler
        wakeup (reference: the lease-amortization idea of SURVEY §3.2's
        hot-loops note, applied to the submit side). Per-task return
        value shape matches submit_task."""
        self._drain_out_of_scope()
        store_contains = self.memory_store.contains
        owned: List[tuple] = []
        all_deps: List[ObjectID] = []
        for spec in specs:
            # env packaging does GCS I/O — never under a refcount lock
            if spec.runtime_env and "working_dir" in spec.runtime_env:
                spec.runtime_env = self.prepare_runtime_env(
                    spec.runtime_env)
            for oid in spec.return_ids():  # id-keyed memo inside
                owned.append((oid, spec.task_id))
            deps = (_top_level_deps(spec.args, spec.kwargs)
                    if (spec.args or spec.kwargs) else [])
            spec._deps_memo = deps
            if deps:
                self._stamp_arg_sizes(spec, deps)
            all_deps.extend(deps)
        self.reference_counter.register_submit_batch(owned, all_deps)
        self.task_manager.add_pending_batch(specs)
        if self.qos_plane is not None:
            for spec in specs:
                self.qos_plane.note_queued(spec.task_id, spec.tenant,
                                           spec.priority)
        self.events.record_batch(((s.task_id, s.name) for s in specs),
                                 "submitted")
        # trace stamping BEFORE the task-event records (detail rows
        # carry the trace context stamped here)
        if self.trace_plane is not None:
            self.trace_plane.on_submit_batch(
                s for s in specs if s.task_type == TaskType.NORMAL_TASK)
        if self.task_events is not None:
            self.task_events.record_submitted_batch(
                s for s in specs if s.task_type == TaskType.NORMAL_TASK)
        pendings: List[PendingTask] = []
        out: List[List[ObjectRef]] = []
        for spec in specs:
            unresolved = []
            for d in spec._deps_memo:
                if store_contains(d):
                    continue
                unresolved.append(d)
                self.object_recovery.maybe_recover(d)
            pendings.append(PendingTask(spec=spec, deps=unresolved,
                                        execute=_noop_exec))
            if spec.timeout_s:
                self._register_deadline(spec)
            refs = []
            for oid in spec.return_ids():
                ref = ObjectRef(oid, self.worker_id, _register=False)
                ref._weak = False  # counted in register_submit_batch
                if runtime_sanitizer._ENABLED:
                    runtime_sanitizer.track_ref(ref)
                refs.append(ref)
            out.append(refs)
        self.scheduler.submit_many(pendings)
        return out

    def _stamp_arg_sizes(self, spec: TaskSpec, deps: List[ObjectID]) -> None:
        """Per-arg (ObjectID, nbytes) summary for locality scoring and
        dispatch-time staging. Only stamped when remote arenas exist and
        the knob is on: single-node runs (and locality-off runs) skip
        the per-dep size lookups entirely, keeping submit byte-for-byte
        pre-locality."""
        if not self._has_remote_nodes \
                or not GLOBAL_CONFIG.scheduler_locality:
            return
        get_entry = self.memory_store.get_entry
        sizes = []
        for d in deps:
            e = get_entry(d)
            # 0 = size unknown (dep not yet produced); the scheduler
            # still counts the copy, weighted minimally
            sizes.append((d, e.size if e is not None else 0))
        spec.arg_sizes = tuple(sizes)

    def cancel_task(self, ref: ObjectRef, force: bool = False) -> None:
        task_id = ref.task_id()
        if self.scheduler.cancel(task_id):
            err = rex.TaskCancelledError(task_id)
            # resolve ALL the task's return refs, not just the one passed
            # in — a get() on a sibling return must not hang forever
            spec = self.task_manager.get_pending_spec(task_id)
            return_ids = (spec.return_ids() if spec is not None
                          else [ref.object_id()])
            for oid in return_ids:
                self.memory_store.put(oid, err, is_exception=True)
                self.scheduler.notify_object_ready(oid)
            self.task_manager.complete(task_id)
            return
        if self.process_pool is not None \
                and self.process_pool.cancel(task_id, force):
            return  # running in a worker process: flagged or killed there
        with self._running_lock:
            running = task_id in self._running_tasks
            if running:
                # cooperative flag read via was_current_task_cancelled
                self._running_tasks[task_id] = True
            elif self.task_manager.get_pending_spec(task_id) is not None:
                # leased through the dispatch window but still queued in
                # the executor: mark for cancellation at execution start
                self._precancelled.add(task_id)
        if running and force:
            _async_raise_in_task(task_id)

    def was_current_task_cancelled(self) -> bool:
        task_id = self._context.task_id
        if task_id is None:
            return False
        # dict.get is GIL-atomic; the value is a plain bool flag
        return bool(self._running_tasks.get(task_id, False))

    # ------------------------------------------------------------------
    # Execution (dispatcher target)
    # ------------------------------------------------------------------
    def pool_for_node(self, node_index: int):
        """ProcessWorkerPool backing a scheduler row (bundle rows resolve
        through their parent node), or None for host-local execution."""
        pool = self._node_pools.get(node_index)
        if pool is not None:
            return pool
        ns = self.scheduler.node_state(node_index)
        if ns is not None and ns.is_bundle and ns.parent >= 0:
            return self._node_pools.get(ns.parent)
        return None

    def _chaos_tick(self) -> None:
        """Dispatch-path injection point: the testing_tick_delay_s knob
        (re-read live) plus the chaos ``sched_tick`` site, both
        simulating a slow scheduling node."""
        d = self._tick_delay_entry.value
        if d > 0.0:
            time.sleep(d)
        fault = self._chaos.poll("sched_tick")
        if fault is not None:
            time.sleep(fault.get("delay_s", 0.05))

    def _stage_args(self, pool, pending: PendingTask) -> None:
        """Dispatch-time arg staging: args NOT resident on the assigned
        node but resident on a peer with a transfer endpoint ship their
        known locations with the lease, so the daemon's pull manager
        overlaps the peer pull with the task's queue wait (instead of
        paying the transfer at exec start). Also keeps the locality
        hit/miss and bytes-saved/pulled accounting."""
        sizes = getattr(pending.spec, "arg_sizes", None)
        if not sizes:
            return
        stage: List[tuple] = []
        resident = 0
        missing = 0
        located = 0
        for oid, nbytes in sizes:
            locs = self.gcs.object_locations(oid)
            if not locs:
                continue  # head-resident: embedded in the lease payload
            located += 1
            if pool.node_index in locs:
                resident += nbytes
                continue
            missing += 1
            for src in locs:
                peer = self.peer_address_of(src)
                if peer is not None:
                    stage.append((oid.binary(), tuple(peer), nbytes))
                    break
        if located:
            self.note_transfer(
                "locality_misses" if missing else "locality_hits")
            if resident:
                self.note_transfer("bytes_saved", resident)
        if stage:
            pool.stage_args(stage)
            if self.task_events is not None:
                self.task_events.record_staged(pending.spec.task_id,
                                               pending.node_index)
            if self.trace_plane is not None:
                self.trace_plane.record_staged(pending.spec.task_id,
                                               pending.node_index)

    def _dispatch(self, pending: PendingTask) -> None:
        self._chaos_tick()
        self.events.record(pending.spec.task_id, pending.spec.name,
                           "dispatched", pending.node_index,
                           attempt=pending.spec.attempt_number)
        te = self.task_events
        if te is not None:
            te.record_dispatched_batch(
                ((pending.spec.task_id, pending.node_index),))
        if self.trace_plane is not None:
            self.trace_plane.record_dispatched_batch(
                ((pending.spec.task_id, pending.node_index),))
        boot = getattr(pending.spec, "_actor_boot", None)
        pool = self.pool_for_node(pending.node_index)
        if boot is not None:
            self._pool.submit(self._boot_actor, pending, boot)
        elif (pool is not None
              and pending.spec.task_type == TaskType.NORMAL_TASK):
            if pool.is_remote:
                self._stage_args(pool, pending)
            # lease grant: the decision becomes a payload shipped to a
            # worker process on the ASSIGNED node (payload build + pipe
            # send run OFF the tick thread: a full pipe buffer blocks
            # the send, and a blocked tick thread would stall all
            # scheduling — the batch path amortizes the executor hop)
            self._pool.submit(pool.run_task, pending)
        else:
            self._pool.submit(self._execute_task, pending)

    def _dispatch_many(self, pendings: List[PendingTask]) -> None:
        """One tick's grants: normal tasks bound for local process
        pools batch into per-pool lease grants (one executor hop and
        one pipe message per worker per tick, instead of per task);
        everything else takes the per-task path."""
        self._chaos_tick()
        groups: Dict[Any, List[PendingTask]] = {}
        local: List[tuple] = []
        fast: List[PendingTask] = []
        te = self.task_events
        tp = self.trace_plane
        te_rows: List[tuple] = []
        # profile-event rows batch per node (record_batch takes one
        # node): one ring append pass per tick, not one call per task
        ev_rows: Dict[int, List[tuple]] = {}
        for pending in pendings:
            spec = pending.spec
            pool = self.pool_for_node(pending.node_index)
            if (getattr(spec, "_actor_boot", None) is not None
                    or spec.task_type != TaskType.NORMAL_TASK):
                self._dispatch(pending)
            elif pool is not None and not pool.is_remote:
                ev_rows.setdefault(pending.node_index, []).append(
                    (spec.task_id, spec.name))
                if te is not None or tp is not None:
                    te_rows.append((spec.task_id, pending.node_index))
                groups.setdefault(pool, []).append(pending)
            elif pool is None:
                # host-thread execution. Plain tasks (no deps to
                # resolve, no runtime env, no placement group, single
                # return) take the drain fast path: a SHARED deque that
                # every executor thread pulls from one task at a time —
                # work stealing is preserved (pre-chunking per thread
                # would let a blocking task head-of-line its chunk;
                # worst case: deadlock a producer queued behind its own
                # consumer) while completion bookkeeping amortizes
                # per-drain instead of per-task
                if (not spec.runtime_env
                        and spec.placement_group_id is None
                        and spec.num_returns == 1
                        and not spec.kwargs
                        and not getattr(spec, "_deps_memo", None)):
                    fast.append(pending)
                else:
                    ev_rows.setdefault(pending.node_index, []).append(
                        (spec.task_id, spec.name))
                    if te is not None or tp is not None:
                        te_rows.append((spec.task_id,
                                        pending.node_index))
                    local.append((self._execute_task, (pending,)))
            else:
                self._dispatch(pending)
        for node, rows in ev_rows.items():
            self.events.record_batch(rows, "dispatched", node)
        if te_rows or fast:
            all_rows = te_rows + [(p.spec.task_id, p.node_index)
                                  for p in fast]
            if te is not None:
                te.record_dispatched_batch(all_rows)
            if tp is not None:
                tp.record_dispatched_batch(all_rows)
        if fast:
            self.events.record_batch(
                ((p.spec.task_id, p.spec.name) for p in fast),
                "dispatched")
            dq: collections.deque = collections.deque(fast)
            k = min(self._pool.num_threads, len(fast))
            self._pool.submit_many(
                [(self._drain_local_batch, (dq,))] * k)
        if local:
            self._pool.submit_many(local)
        for pool, batch in groups.items():
            self._pool.submit(self._run_pool_batch, pool, batch)

    def _drain_local_batch(self, dq) -> None:
        """Fast-path executor drain: plain no-dep NORMAL tasks from one
        tick's grants. Per task it does only the irreducible work —
        cancel-registry bracket, the user function, the result put, and
        the scheduler notification (slot release must never wait on a
        batch, or a blocked sibling could deadlock dependants).
        Everything deferrable — task-manager lineage completion — is
        flushed per drain. The deque is SHARED with the other executor
        threads: each pops one task at a time, so a blocking task
        stalls only itself (see _dispatch_many)."""
        running = self._running_tasks
        rlock = self._running_lock
        record = self.events.record
        put = self.memory_store.put
        notify = self.scheduler.notify_batch
        ctx = self._context
        prev_task = ctx.task_id
        prev_put = ctx.put_counter
        complete = self.task_manager.complete_batch_with_refs
        has_ref = self.reference_counter.has_reference
        done: List[tuple] = []
        te = self.task_events
        te_done: List[tuple] = []
        tp = self.trace_plane
        tp_done: List[tuple] = []
        wkey = threading.get_ident()
        try:
            while True:
                try:
                    pending = dq.popleft()
                except IndexError:
                    break
                spec = pending.spec
                exec_id = spec.task_id
                pre_timed_out = False
                with rlock:
                    running[exec_id] = False
                    if self._precancelled \
                            and exec_id in self._precancelled:
                        self._precancelled.discard(exec_id)
                        running[exec_id] = True
                    elif self._pretimeout \
                            and exec_id in self._pretimeout:
                        self._pretimeout.discard(exec_id)
                        pre_timed_out = True
                ctx.task_id = exec_id
                ctx.put_counter = 0
                record(exec_id, spec.name, "started", pending.node_index)
                rids = (getattr(spec, "_retry_return_ids", None)
                        or spec.return_ids())  # id-keyed memo inside
                retry_task = None
                ready = ()
                try:
                    if pre_timed_out:
                        # deadline expired while executor-queued: fail
                        # the attempt (retriably) without running it
                        if self._claim_task_completion(exec_id) != "timeout":
                            retry_task = self._handle_task_failure(
                                spec, rids, rex.TaskTimeoutError(
                                    f"task {spec.name} timed out after "
                                    f"{spec.timeout_s}s before starting",
                                    task_id=exec_id,
                                    timeout_s=spec.timeout_s))
                    elif running.get(exec_id) == "timeout":
                        pass  # watcher already failed/retried it
                    elif running.get(exec_id):
                        self._store_error(
                            spec, rids, rex.TaskCancelledError(exec_id))
                    else:
                        try:
                            self._maybe_inject_failure()
                            t0 = time.time()
                            with trace_plane.parent_scope(
                                    spec.trace_ctx if tp is not None
                                    else None):
                                result = spec.func(*spec.args)
                            t1 = time.time()
                        except BaseException as e:  # noqa: BLE001
                            flag = self._claim_task_completion(exec_id)
                            if flag == "timeout":
                                pass  # watcher already failed/retried it
                            elif flag:
                                # cancelled mid-run: never retry
                                self._store_error(
                                    spec, rids,
                                    rex.TaskCancelledError(exec_id))
                            else:
                                retry_task = self._handle_task_failure(
                                    spec, rids, e)
                        else:
                            flag = self._claim_task_completion(exec_id)
                            if flag == "timeout":
                                pass  # retry owns the return ids now
                            elif flag:
                                # cancel landed mid-run: drop the result
                                self._store_error(
                                    spec, rids,
                                    rex.TaskCancelledError(exec_id))
                            else:
                                put(rids[0], result)
                                ready = (rids[0],)
                                done.append((exec_id, rids[0]))
                                if te is not None:
                                    te_done.append(
                                        (exec_id, (t0, t1), wkey,
                                         pending.node_index))
                                if (tp is not None
                                        and spec.trace_ctx is not None
                                        and spec.trace_ctx[3]):
                                    tp_done.append(
                                        (exec_id, (t0, t1), wkey,
                                         pending.node_index))
                finally:
                    with rlock:
                        running.pop(exec_id, None)
                    record(exec_id, spec.name, "finished",
                           pending.node_index)
                    notify(ready, ((exec_id, pending.node_index,
                                    spec.resources),))
                    if retry_task is not None:
                        # finished-notification already out: the
                        # scheduler sees the slot release before the
                        # retry (same ordering as _execute_task)
                        if done:
                            complete(done, has_ref)
                            done = []
                        self._submit_retry(retry_task)
                if len(done) >= 256:
                    complete(done, has_ref)
                    done = []
                if len(te_done) >= 256:
                    te.record_finished_batch(te_done)
                    te_done = []
                if len(tp_done) >= 256:
                    tp.record_finished_batch(tp_done)
                    tp_done = []
        finally:
            ctx.task_id = prev_task
            ctx.put_counter = prev_put
            if done:
                complete(done, has_ref)
            if te_done:
                te.record_finished_batch(te_done)
            if tp_done:
                tp.record_finished_batch(tp_done)
            self.placement_groups.poke()

    def _run_pool_batch(self, pool, batch: List[PendingTask]) -> None:
        try:
            pool.run_task_batch(batch)
        except Exception:
            logger.exception("batch dispatch failed on node %d",
                             batch[0].node_index)

    def _boot_actor(self, pending: PendingTask, boot) -> None:
        try:
            boot(pending, pending.node_index)
        except Exception:
            logger.exception("actor bootstrap failed")

    # ------------------------------------------------------------------
    # Virtual multi-node (reference: python/ray/cluster_utils.py — each
    # added node is a REAL per-node runtime: its own exec'd worker
    # processes behind its own pool, with declared resources)
    # ------------------------------------------------------------------
    def add_cluster_node(self, num_cpus: float = 4.0, num_tpus: float = 0.0,
                         num_workers: Optional[int] = None,
                         resources: Optional[Dict[str, float]] = None):
        from ray_tpu._private.runtime.process_pool import ProcessWorkerPool
        from ray_tpu._private.runtime.shm_store import ShmObjectStore

        if self.shm_store is None:
            # thread-mode head: the cluster's shared object arena appears
            # with the first process-backed node
            self.shm_store = ShmObjectStore(GLOBAL_CONFIG.object_store_memory)
        custom = sum((resources or {}).values())
        node_id = NodeID.from_random()
        from ray_tpu._private.runtime.process_pool import auto_pipeline_depth
        nw = num_workers or max(int(num_cpus), 1)
        state = NodeState((num_cpus, num_tpus, 1e18, custom),
                          node_id=node_id, custom_resources=resources,
                          window_factor=auto_pipeline_depth(nw))
        row = self.scheduler.add_node(state, wake=False)
        pool = ProcessWorkerPool(self, nw,
                                 self.shm_store, node_index=row)
        self._node_pools[row] = pool
        self.scheduler.poke()
        entry = self.gcs.register_node(
            node_id, row, {"CPU": num_cpus, "TPU": num_tpus,
                           **(resources or {})},
            kind="process", pool=pool)
        self.gcs.start_health_checks()
        return entry

    def add_remote_cluster_node(self, num_cpus: float = 4.0,
                                num_tpus: float = 0.0,
                                num_workers: Optional[int] = None,
                                resources: Optional[Dict[str, float]] = None,
                                object_store_memory: Optional[int] = None):
        """Add a node backed by a NODE DAEMON process with its OWN shm
        arena, connected over TCP (localhost stands in for the DCN) —
        the real multi-host topology, unlike add_cluster_node's
        same-process pools sharing the head arena. Reference: one
        raylet+plasma per node, registered with the GCS over the
        network."""
        import subprocess
        import sys

        from ray_tpu._private.runtime.remote_pool import (HeadServer,
                                                          RemoteNodePool)

        if self._head_server is None:
            self._head_server = HeadServer()
        # a severed daemon link (chaos flap, transient network drop)
        # comes back as an UNSOLICITED rejoin hello — without the hook
        # the accept loop would silently close it and the node would
        # burn its whole REJOINING grace window dialing a deaf head
        self._head_server.on_unsolicited = self._on_unsolicited_hello
        token = self._head_server.issue_token()
        slot_ev, slot = self._head_server.expect(token)
        # the daemon (and the workers it spawns) never owns the head's
        # chip lease; strip accelerator plugin vars so a degraded tunnel
        # can't hang its `import jax` (see spawn_env docstring)
        from ray_tpu._private import log_plane, spawn_env
        extra = {"RAY_TPU_HEAD_AUTHKEY": self._head_server.authkey.hex()}
        if GLOBAL_CONFIG.profile_hz > 0:
            # hand the daemon the head's live profile knobs (they may
            # have arrived via _system_config, not env) so it starts
            # its utilization sampler and re-exports to its workers
            extra["RAY_TPU_PROFILE_HZ"] = str(GLOBAL_CONFIG.profile_hz)
            extra["RAY_TPU_UTILIZATION_INTERVAL_S"] = str(
                GLOBAL_CONFIG.utilization_interval_s)
        if self.session_log_dir is not None:
            # the daemon's own node log dir nests under the head's
            # session dir (same-host clusters; a true remote host just
            # creates the path locally), and the daemon's own
            # stdout/stderr capture files live inside it
            node_dir = os.path.join(self.session_log_dir,
                                    f"node-{token[:8]}")
            extra["RAY_TPU_LOG_DIR"] = node_dir
            extra.update(log_plane.child_log_env(
                node_dir, f"node_daemon-{token[:8]}",
                GLOBAL_CONFIG.log_rotation_bytes,
                GLOBAL_CONFIG.log_rotation_backups))
        env = spawn_env.child_env(
            inherit_sys_path=True,
            extra=extra)
        host, port = self._head_server.address
        import json as _json
        info = _json.dumps({"num_cpus": num_cpus, "num_tpus": num_tpus,
                            "resources": resources or {},
                            "num_workers": num_workers
                            or max(int(num_cpus), 1)})
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.runtime.node_daemon",
             host, str(port), token,
             str(object_store_memory
                 or GLOBAL_CONFIG.object_store_memory),
             str(GLOBAL_CONFIG.inline_object_max_bytes),
             info, str(GLOBAL_CONFIG.daemon_rejoin_timeout_s)],
            env=env, close_fds=True,
            # its own session: the daemon leads a process group holding
            # its whole worker tree, so machine-death chaos can killpg
            # the entire "machine" at once (and a SIGINT at the driver
            # terminal never reaches the simulated remote node)
            start_new_session=True)
        if not slot_ev.wait(timeout=30.0) or not slot:
            proc.kill()
            raise RuntimeError("node daemon failed to register with the "
                               "head within 30s")
        conn, hello = slot[0], slot[1]
        arena_name = hello[3] if len(hello) > 3 else None
        peer_address = hello[4] if len(hello) > 4 else None
        custom = sum((resources or {}).values())
        node_id = NodeID.from_random()
        state = NodeState((num_cpus, num_tpus, 1e18, custom),
                          node_id=node_id, custom_resources=resources)
        # row wiring order: the pool must be reachable through
        # pool_for_node BEFORE the scheduler may dispatch to the row, or
        # a pending task/actor lands on a half-registered node
        row = self.scheduler.add_node(state, wake=False)
        pool = RemoteNodePool(self, num_workers or max(int(num_cpus), 1),
                              row, conn, node_id, daemon_proc=proc,
                              arena_name=arena_name,
                              peer_address=peer_address)
        self._node_pools[row] = pool
        self._has_remote_nodes = True
        self.scheduler.poke()
        entry = self.gcs.register_node(
            node_id, row, {"CPU": num_cpus, "TPU": num_tpus,
                           **(resources or {})},
            kind="remote", pool=pool)
        self.gcs.start_health_checks()
        self._ensure_resview_push()
        return entry

    def enable_head_endpoint(self, host: str = "127.0.0.1", port: int = 0):
        """Open (or return) the head's TCP endpoint and accept
        UNSOLICITED registrations: remote clients (`ray://` sessions)
        and joining node daemons (`ray_tpu start --address=...`).
        Returns the HeadServer; its address/authkey form the connect
        string."""
        from ray_tpu._private.client import ClientServer
        from ray_tpu._private.runtime.remote_pool import HeadServer

        if self._head_server is not None:
            cur_host, cur_port = self._head_server.address
            if (port != 0 and port != cur_port) or host != cur_host:
                raise RuntimeError(
                    f"head endpoint already bound to {cur_host}:{cur_port} "
                    f"(created when the first remote node was added); call "
                    f"enable_head_endpoint(host=..., port=...) BEFORE "
                    f"adding remote nodes to pick the bind address")
        if self._head_server is None:
            authkey = None
            if GLOBAL_CONFIG.gcs_journal_path:
                # persist (port, authkey) beside the journal: after a
                # head restart, orphaned daemons re-dial the SAME
                # address with the SAME cluster secret
                import json as _json
                secret_path = GLOBAL_CONFIG.gcs_journal_path + ".secret"
                if os.path.exists(secret_path):
                    with open(secret_path) as f:
                        d = _json.load(f)
                    authkey = bytes.fromhex(d["authkey"])
                    if port == 0:
                        port = int(d["port"])
                self._head_server = HeadServer(host, port, authkey=authkey)
                # the authkey is the cluster credential: owner-only
                # permissions, like ssh key material
                fd = os.open(secret_path,
                             os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
                with os.fdopen(fd, "w") as f:
                    _json.dump({"authkey":
                                self._head_server.authkey.hex(),
                                "port": self._head_server.address[1]}, f)
            else:
                self._head_server = HeadServer(host, port)
        if self.client_server is None:
            self.client_server = ClientServer(self)
        self._head_server.on_unsolicited = self._on_unsolicited_hello
        return self._head_server

    def _on_unsolicited_hello(self, conn, hello: tuple) -> None:
        kind = hello[1]
        if kind == "client":
            self.client_server.attach(conn, hello)
        elif kind == "join" and len(hello) >= 5:
            self.adopt_remote_node(conn, hello)
        elif kind == "rejoin" and len(hello) >= 7:
            pool = self._find_rejoin_pool(hello[3])
            if pool is not None:
                # link flap, not a head restart: THIS head already runs
                # the node — its pool (leases, refs, worker handles) is
                # intact, so only the transport swaps. The daemon's
                # outbox replay follows and the sequence dedup drops
                # everything this head already processed.
                pool.reattach(conn)
            else:
                self.readopt_remote_node(conn, hello)
        else:
            conn.close()

    def _find_rejoin_pool(self, arena_name):
        """Match a rejoin hello to a pool this head already owns (by
        arena name — unique per daemon). A true head restart has no
        pools to match and falls through to full re-adoption."""
        if not arena_name:
            return None
        for pool in list(self._node_pools.values()):
            if getattr(pool, "is_remote", False) \
                    and getattr(pool, "_arena_name", None) == arena_name \
                    and not pool._node_dead:
                return pool
        return None

    def adopt_remote_node(self, conn, hello: tuple):
        """A node daemon started out-of-band (`ray_tpu start
        --address=head:port`) registers itself: same runtime as
        add_remote_cluster_node but the daemon process belongs to
        another launcher (possibly another machine)."""
        from ray_tpu._private.runtime.remote_pool import RemoteNodePool

        arena_name, info = hello[3], hello[4]
        peer_address = hello[5] if len(hello) > 5 else None
        num_cpus = float(info.get("num_cpus", 4.0))
        num_tpus = float(info.get("num_tpus", 0.0))
        resources = dict(info.get("resources") or {})
        num_workers = int(info.get("num_workers") or max(int(num_cpus), 1))
        node_id = NodeID.from_random()
        state = NodeState((num_cpus, num_tpus, 1e18,
                           sum(resources.values())),
                          node_id=node_id, custom_resources=resources)
        row = self.scheduler.add_node(state, wake=False)
        # arena_name travels so a SAME-host joined daemon's segment can
        # be reaped after death (on another host the name matches
        # nothing here and the reap is a no-op)
        pool = RemoteNodePool(self, num_workers, row, conn, node_id,
                              daemon_proc=None, arena_name=arena_name,
                              peer_address=peer_address)
        self._node_pools[row] = pool
        self._has_remote_nodes = True
        self.scheduler.poke()
        entry = self.gcs.register_node(
            node_id, row, {"CPU": num_cpus, "TPU": num_tpus, **resources},
            kind="remote", pool=pool)
        self.gcs.start_health_checks()
        self._ensure_resview_push()
        logger.info("adopted remote node %s (row %d, arena %s)",
                    node_id.hex()[:16], row, arena_name)
        return entry

    def readopt_remote_node(self, conn, hello: tuple):
        """Control-plane FT, node side: an orphaned daemon (its head
        died without an exit) rejoins a RESTARTED head. Its live worker
        processes are adopted instead of respawned, and dedicated
        workers hosting journaled (detached) actors get their runtimes
        re-attached — actor state survives the head restart inside the
        worker process (reference: GCS restart with Redis replay while
        raylets keep running, SURVEY.md §5 GCS FT)."""
        from ray_tpu._private.ids import ActorID
        from ray_tpu._private.runtime.remote_pool import RemoteNodePool

        _, _, pid, arena_name, info, peer_address, workers = hello[:7]
        num_cpus = float(info.get("num_cpus", 4.0))
        num_tpus = float(info.get("num_tpus", 0.0))
        resources = dict(info.get("resources") or {})
        # epoch fence: a daemon whose node this head already DECLARED
        # DEAD (partition outlived the grace window) comes back as a
        # fresh node, but nothing from its dead era may resolve — the
        # node-death reconciler already resubmitted or failed every
        # adopted lease and restarted its actors elsewhere. The fenced
        # pool acks-but-drops outbox REPLAY envelopes, no dead-era
        # lease is re-attached, and the ("fence", epoch) frame below
        # tells the daemon to clear its dead-era local-lease state.
        fenced = bool(arena_name) and arena_name in self._fenced_arenas
        node_id = NodeID.from_random()
        state = NodeState((num_cpus, num_tpus, 1e18,
                           sum(resources.values())),
                          node_id=node_id, custom_resources=resources)
        row = self.scheduler.add_node(state, wake=False)
        pool = RemoteNodePool(self, 0, row, conn, node_id,
                              daemon_proc=None, arena_name=arena_name,
                              peer_address=peer_address, fenced=fenced)
        self._node_pools[row] = pool
        self._has_remote_nodes = True
        adopted_actors = 0
        adopted_leases = 0
        for num, winfo in sorted(workers.items()):
            actor_hex = winfo.get("actor")
            inflight = winfo.get("inflight") or {}
            h = pool.adopt_worker(int(num), winfo.get("pid"),
                                  is_actor=actor_hex is not None,
                                  busy=bool(inflight) and not fenced)
            if fenced:
                # dead-era state is unwanted: stale in-flight results
                # find no inflight entry and drop; actor workers are
                # released (their actors already restarted elsewhere
                # or went DEAD when the node did)
                if actor_hex is not None:
                    pool.release_actor_worker(h, kill=True)
                continue
            if actor_hex is None:
                # lease reconciliation: tasks this worker still RUNS
                # re-attach as synthetic inflight entries under their
                # ORIGINAL return oids, so the done/err (live or outbox
                # replay) resolves the refs a resumed client is blocked
                # on. Attempt skew (journal ahead of the report) means
                # the old head re-dispatched the task elsewhere before
                # dying: leave the record for its real claimant and let
                # this worker's stale result drop (no inflight entry).
                for tid_hex, rep in inflight.items():
                    tid_bin = bytes.fromhex(tid_hex)
                    rep_attempt = int(rep.get("attempt", 0))
                    lease = self.gcs.claim_lease(tid_bin)
                    if lease is not None \
                            and int(lease.get("attempt", 0)) != rep_attempt:
                        self.gcs.journal_lease(tid_bin, lease)
                        continue
                    returns = [bytes.fromhex(x)
                               for x in rep.get("returns", [])]
                    for rbin in returns:
                        self.reference_counter.add_owned_object(
                            ObjectID(rbin))
                    pool.adopt_inflight(h, tid_bin, returns, rep_attempt)
                    adopted_leases += 1
                continue
            actor_id = ActorID(bytes.fromhex(actor_hex))
            entry = self.gcs.orphaned_actor(actor_id)
            recovery = self.gcs.actor_recovery_blob(actor_id)
            if entry is None or recovery is None:
                # not a journaled detached actor: its owner died with
                # the old head — release the worker
                pool.release_actor_worker(h, kill=True)
                continue
            try:
                from ray_tpu.actor import adopt_process_actor
                adopt_process_actor(self, actor_id, entry, recovery,
                                    pool, h, row)
                adopted_actors += 1
            except Exception:
                logger.exception("actor %s re-adoption failed",
                                 actor_id.hex()[:16])
                pool.release_actor_worker(h, kill=True)
        if fenced:
            # the daemon clears its dead-era local-lease/outbox/p2p
            # bookkeeping so no zombie re-lease or stale fallback ever
            # resurfaces; the epoch value is an opaque fence token for
            # the daemon's log
            pool._send_daemon(("fence", int(time.monotonic() * 1000)))
            self._fenced_arenas.pop(arena_name, None)
        # plain workers survive with their leases now (the daemon no
        # longer kills mid-task workers at rejoin); still top up to the
        # node's declared worker count so the row never advertises CPUs
        # with no process to run on
        target = int(info.get("num_workers") or max(int(num_cpus), 1))
        plain = sum(1 for w in workers.values() if not w.get("actor"))
        for _ in range(max(0, target - plain)):
            h = pool._spawn()  # takes the pool lock itself
            with pool._lock:
                pool._handles.append(h)
        entry = self.gcs.register_node(
            node_id, row, {"CPU": num_cpus, "TPU": num_tpus, **resources},
            kind="remote", pool=pool)
        self.gcs.start_health_checks()
        self.scheduler.poke()
        self._ensure_resview_push()
        logger.info("re-adopted node %s (row %d)%s: %d workers, "
                    "%d actors, %d in-flight leases",
                    node_id.hex()[:16], row,
                    " FENCED (rejoin after declared dead)" if fenced
                    else "", len(workers), adopted_actors,
                    adopted_leases)
        self._start_failover_reconciler()
        return entry

    # ------------------------------------------------------------------
    # head-failover lease reconciliation (the resubmission half;
    # readopt_remote_node above re-attaches the leases survivors claim)
    # ------------------------------------------------------------------
    def _start_failover_reconciler(self) -> None:
        """One-shot, kicked by the first post-restart rejoin: wait for
        the rest of the pre-crash daemons (count-based — rejoined
        daemons carry fresh NodeIDs, so identity can't match) and then
        resubmit every journaled lease no survivor claimed."""
        if self._failover_reconciler_started:
            return
        self._failover_reconciler_started = True
        if not self.gcs.journal_enabled:
            return
        threading.Thread(target=self._reconcile_failover_leases,
                         args=(self.gcs.replayed_node_count,),
                         daemon=True,
                         name="ray_tpu_failover_reconcile").start()

    def _reconcile_failover_leases(self, expected: int) -> None:
        deadline = time.monotonic() + GLOBAL_CONFIG.daemon_rejoin_grace_s
        while time.monotonic() < deadline:
            alive = sum(1 for e in self.gcs.node_table()
                        if e.kind == "remote" and e.state == "ALIVE")
            if alive >= expected:
                break
            time.sleep(0.2)
        unclaimed = self.gcs.pending_leases()
        resub = 0
        for tid_bin, rec in unclaimed.items():
            if self.gcs.claim_lease(tid_bin) is None:
                continue  # a late rejoin claimed it under us
            self.gcs.journal_lease_done(tid_bin)  # consumed either way
            if self._resubmit_lease(tid_bin, rec):
                resub += 1
        if unclaimed:
            logger.warning(
                "head failover: %d journaled leases unclaimed by "
                "rejoining nodes; %d resubmitted", len(unclaimed), resub)

    def _resubmit_lease(self, tid_bin: bytes, rec: dict,
                        why: str = "head failover") -> bool:
        """Rebuild a TaskSpec from a retained/journaled lease record
        and submit it under the ORIGINAL return oids with a bumped
        attempt token — a stale replay of the dead attempt finds no
        inflight entry and drops, so the task's side effects run at
        most once post-recovery. Records without a resubmittable body
        fail their refs instead of hanging the owner's get()."""
        import cloudpickle

        returns = [ObjectID(b) for b in rec.get("returns", [])]
        name = rec.get("name") or "failover_resubmit"
        fn_blob, args_blob = rec.get("fn_blob"), rec.get("args_blob")
        try:
            if fn_blob is None or args_blob is None:
                raise ValueError("lease record has no resubmit body")
            func = cloudpickle.loads(fn_blob)
            args, kwargs = cloudpickle.loads(args_blob)
        except Exception as e:
            exc = rex.WorkerCrashedError(
                f"task {name} was in flight on a dead node ({why}), "
                f"and its lease record cannot be resubmitted ({e})")
            for oid in returns:
                self.reference_counter.add_owned_object(oid)
                self.memory_store.put(oid, exc, is_exception=True)
                self.scheduler.notify_object_ready(oid)
            return False
        spec = TaskSpec(
            task_id=self.next_task_id(),
            name=name,
            func=func,
            func_descriptor=name,
            args=args,
            kwargs=kwargs,
            num_returns=int(rec.get("num_returns", len(returns) or 1)),
            resources=dict(rec.get("resources") or {"CPU": 1}),
            max_retries=int(rec.get("max_retries", 0)),
            serialized_func=fn_blob,
            attempt_number=int(rec.get("attempt", 0)) + 1,
        )
        spec._retry_return_ids = returns  # type: ignore[attr-defined]
        for oid in returns:
            self.reference_counter.add_owned_object(
                oid, lineage_task=spec.task_id)
        self.task_manager.add_pending(spec, [])
        self.scheduler.submit(PendingTask(spec=spec, deps=[],
                                          execute=_noop_exec))
        logger.warning("%s: resubmitting %s (lease %s, attempt %d)",
                       why, name, tid_bin.hex()[:16],
                       spec.attempt_number)
        return True

    def on_node_failure(self, node_id: NodeID, reason: str = "") -> None:
        """Node death: mark dead, stop scheduling to it, fail/retry its
        in-flight work, reschedule its placement-group bundles, and fail
        or restart its actors (reference: NodeManager/GcsNodeManager
        death handling + lineage-driven resubmission)."""
        entry = None
        for e in self.gcs.node_table():
            if e.node_id == node_id:
                entry = e
                break
        if entry is None or entry.state == "DEAD":
            return
        self.gcs.mark_node_dead(node_id, reason)
        # 1) no new assignments to the node (also invalidates in-flight
        #    snapshot decisions at apply time)
        self.scheduler.remove_node(entry.index)
        # 1b) the dead node's copies leave the object directory. Objects
        #     whose LAST copy died are LOST unless already
        #     fetched/memoized head-side — drop them so a later get()
        #     reconstructs from lineage. Objects with a surviving
        #     secondary (a completed staging pull) promote it to primary
        #     instead: the head's placeholder repoints and no
        #     reconstruction is needed.
        from ray_tpu._private.runtime.process_pool import RemotePlaceholder
        lost, promoted = self.gcs.drop_node_locations(entry.index)
        for oid in lost:
            e = self.memory_store.get_entry(oid)
            if e is not None and not e.is_exception \
                    and isinstance(e.value, RemotePlaceholder) \
                    and e.value.node_index == entry.index:
                self.object_recovery.note_freed(oid)
                self.memory_store.delete([oid])
        for oid, new_primary in promoted.items():
            e = self.memory_store.get_entry(oid)
            if e is not None and not e.is_exception \
                    and isinstance(e.value, RemotePlaceholder) \
                    and e.value.node_index == entry.index:
                e.value.node_index = new_primary
        # 2) placement groups with bundles on the node reschedule
        self.placement_groups.on_node_dead(entry.index)
        # 3) two-level plane reconciliation: decide the fate of every
        #    lease the node's LocalScheduler admitted (retry under the
        #    original return oids or fail the refs), release their arg
        #    pins, fence the arena against stale rejoin replays, and
        #    broadcast route invalidation so peers drop cached p2p
        #    routes NOW instead of waiting out the lane-sever timeout.
        self.note_two_level("node_deaths")
        pool = self._node_pools.pop(entry.index, None)
        if pool is not None:
            if getattr(pool, "is_remote", False):
                self._reconcile_orphan_leases(pool, reason)
                arena = getattr(pool, "_arena_name", None)
                if arena:
                    self._fenced_arenas[arena] = time.monotonic()
                self._broadcast_node_death(entry.index, pool)
            # 4) fail queued + running work retriably; kill worker
            #    processes. Monitors drive per-task retries; actor
            #    runtimes observe their worker's death and restart
            #    elsewhere or go DEAD.
            pool.fail_node(reason or "node removed")
        self.placement_groups.poke()

    def _reconcile_orphan_leases(self, pool, reason: str) -> None:
        """Node-death half of adopted-lease reconciliation: claim every
        lease the dead node's LocalScheduler still had in flight and
        route it through :meth:`reconcile_orphan_lease`. Claiming the
        inflight entry here (under the pool lock) keeps the per-worker
        failure sweep from double-handling the same lease when the
        dead daemon's ``__died__`` notifications race this call."""
        tids = pool.take_local_tids()
        retried = 0
        for tid_bin in sorted(tids):
            task_id = TaskID(tid_bin)
            with pool._lock:
                h = pool._by_task.get(task_id)
            if h is None:
                continue
            inf = pool._take_inflight(h, task_id)
            if inf is None:
                continue  # a failure sweep claimed it first
            err = rex.NodeDiedError(
                f"node {pool.node_index} died while running a locally "
                f"dispatched lease: {reason or 'node removed'}")
            if self.reconcile_orphan_lease(
                    tid_bin, [o.binary() for o in inf.return_ids], err):
                retried += 1
        if tids:
            logger.warning(
                "node %d death: %d adopted local leases reconciled "
                "(%d resubmitted, %d failed)", pool.node_index,
                len(tids), retried, len(tids) - retried)

    def reconcile_orphan_lease(self, tid_bin: bytes, return_bins,
                               err: BaseException) -> bool:
        """An adopted local lease lost its worker (or whole node) with
        no daemon-side retry in flight. Popping the retained record is
        the exactly-once arbiter between the node-death reconciler and
        the per-worker failure sweep: the claimant resubmits the lease
        head-side under its ORIGINAL return oids when attempts remain,
        or fails its refs terminally. Arg pins release either way (a
        dead node can never send the resolution that would have freed
        them). Returns True when the lease was resubmitted."""
        with self._local_pin_lock:
            rec = self._local_lease_records.pop(tid_bin, None)
        self.release_local_lease_pins(tid_bin)
        if self.gcs.journal_enabled:
            if self.gcs.claim_lease(tid_bin) is not None:
                self.gcs.journal_lease_done(tid_bin)
        if rec is not None and int(rec.get("attempt", 0)) \
                < int(rec.get("max_retries", 0)):
            if self._resubmit_lease(tid_bin, dict(rec),
                                    why="node death"):
                self.note_two_level("orphan_retried")
                return True
            return False  # _resubmit_lease already failed the refs
        returns = [ObjectID(b) for b in
                   ((rec or {}).get("returns") or return_bins or ())]
        for oid in returns:
            self.memory_store.put(oid, err, is_exception=True)
            self.scheduler.notify_object_ready(oid)
        return False

    def _broadcast_node_death(self, index: int, pool) -> None:
        """Route invalidation: tell every surviving daemon the node is
        gone NOW. Peers evict its gossip view, drop cached p2p actor
        routes to its address, and sweep in-flight lane calls to the
        head path immediately instead of waiting out the 15s p2p
        result timeout."""
        peer = getattr(pool, "peer_address", None)
        info = {"index": index,
                "peer": tuple(peer) if peer else None}
        for p in list(self._node_pools.values()):
            if p is pool or not getattr(p, "is_remote", False):
                continue
            try:
                p._send_daemon(("node_dead", info))
            except Exception:
                pass  # a dying link has nothing to invalidate

    def _execute_task(self, pending: PendingTask) -> None:
        spec = pending.spec
        # retries keep the ORIGINAL return ids so existing refs resolve
        return_ids = getattr(spec, "_retry_return_ids", None) or spec.return_ids()
        # capture the id this execution runs under: a retry mutates
        # spec.task_id, and the scheduler must be notified for THIS id
        # (and only after the retry has a fresh id) or its slot leaks
        exec_task_id = spec.task_id
        pre_timed_out = False
        with self._running_lock:
            # value is the cancellation flag: False = running, flipped
            # to True by cancel_task (an Event per task cost ~2us each)
            self._running_tasks[exec_task_id] = False
            if self._precancelled and exec_task_id in self._precancelled:
                self._precancelled.discard(exec_task_id)
                self._running_tasks[exec_task_id] = True
            elif self._pretimeout and exec_task_id in self._pretimeout:
                self._pretimeout.discard(exec_task_id)
                pre_timed_out = True

        prev_task = self._context.task_id
        prev_put = self._context.put_counter
        self._context.task_id = exec_task_id
        self._context.put_counter = 0
        self.events.record(exec_task_id, spec.name, "started",
                           pending.node_index)
        retry_task: Optional[PendingTask] = None
        ready_oids: List[ObjectID] = []
        pg_token = None
        if spec.placement_group_id is not None \
                and spec.placement_group_capture_child_tasks:
            from ray_tpu.util.placement_group import _current_pg
            pg_token = _current_pg.set(spec.placement_group_id)
        # runtime_env env_vars: set for the task's duration. NOTE thread
        # mode shares one process environment — concurrent tasks with
        # conflicting env_vars can observe each other mid-flight
        # (process workers are the isolated path, as in the reference);
        # depth-counted push/pop guarantees the final restore is correct
        env_vars = (spec.runtime_env.get("env_vars")
                    if spec.runtime_env else None)
        if env_vars:
            env_vars_push(env_vars)
        env_ctx = None
        try:
            if pre_timed_out:
                # deadline expired while executor-queued: fail the
                # attempt (retriably) without running it
                if self._claim_task_completion(exec_task_id) != "timeout":
                    retry_task = self._handle_task_failure(
                        spec, return_ids, rex.TaskTimeoutError(
                            f"task {spec.name} timed out after "
                            f"{spec.timeout_s}s before starting",
                            task_id=exec_task_id,
                            timeout_s=spec.timeout_s))
                return
            try:
                # INSIDE the try: an env build failure (bad pip spec,
                # missing package) must fail the TASK — store the error
                # and let the finally release the slot (the process-
                # worker twin does the same)
                env_ctx = self._enter_runtime_env(spec.runtime_env)
            except Exception as e:
                self._store_error(spec, return_ids, e)
                return
            args, kwargs, dep_error, requeue_deps = self._resolve_args(spec)
            if requeue_deps:
                # lost deps are reconstructing: give the slot back and
                # wait for them through the normal dependency machinery
                # (the finally block releases this execution first)
                self.reference_counter.add_submitted_task_references(
                    getattr(spec, "_deps_memo", None)
                    or _top_level_deps(spec.args, spec.kwargs))
                retry_task = PendingTask(spec=spec, deps=requeue_deps,
                                         execute=_noop_exec)
                return
            if dep_error is not None:
                self._store_error(spec, return_ids, dep_error)
                return
            flag = self._running_tasks.get(exec_task_id)
            if flag == "timeout":
                return  # watcher already failed/retried this attempt
            if flag:
                self._store_error(spec, return_ids,
                                  rex.TaskCancelledError(exec_task_id))
                return
            try:
                self._maybe_inject_failure()
                t0 = time.time()
                with trace_plane.parent_scope(
                        spec.trace_ctx if self.trace_plane is not None
                        else None):
                    result = spec.func(*args, **kwargs)
                t1 = time.time()
            except BaseException as e:  # noqa: BLE001
                flag = self._claim_task_completion(exec_task_id)
                if flag == "timeout":
                    return  # watcher already failed/retried the attempt
                if flag:
                    # cancelled mid-run: the failure is moot, and a
                    # cancelled task must never retry
                    self._store_error(spec, return_ids,
                                      rex.TaskCancelledError(exec_task_id))
                    return
                retry_task = self._handle_task_failure(spec,
                                                       return_ids, e)
                return
            finally:
                # tear the env down BEFORE results publish: a caller
                # unblocked by _store_returns may submit a follow-up
                # task that must not see this env's modules/sys.path
                if env_ctx is not None:
                    env_ctx.__exit__(None, None, None)
                    env_ctx = None
            flag = self._claim_task_completion(exec_task_id)
            if flag == "timeout":
                # the deadline fired mid-run: the watcher already
                # failed/retried the attempt, and the retry owns the
                # return ids now — suppress this zombie's results
                return
            if flag:
                # cancel landed while the func ran (thread mode is
                # cooperative): discard the result
                self._store_error(spec, return_ids,
                                  rex.TaskCancelledError(exec_task_id))
                return
            ready_oids = self._store_returns(spec, return_ids, result)
            if self.task_events is not None:
                # no-op for records _store_returns already failed
                # (num_returns mismatch -> _store_error finalized them)
                self.task_events.record_finished_batch(
                    ((exec_task_id, (t0, t1), threading.get_ident(),
                      pending.node_index),))
            if self.trace_plane is not None:
                self.trace_plane.record_finished_batch(
                    ((exec_task_id, (t0, t1), threading.get_ident(),
                      pending.node_index),))
        finally:
            if env_ctx is not None:
                env_ctx.__exit__(None, None, None)
            if env_vars:
                env_vars_pop(env_vars)
            if pg_token is not None:
                from ray_tpu.util.placement_group import _current_pg
                _current_pg.reset(pg_token)
            self._context.task_id = prev_task
            self._context.put_counter = prev_put
            with self._running_lock:
                self._running_tasks.pop(exec_task_id, None)
            self.events.record(exec_task_id, spec.name, "finished",
                               pending.node_index)
            deps = getattr(spec, "_deps_memo", None)
            if deps is None:
                deps = _top_level_deps(spec.args, spec.kwargs)
            if deps:
                self.reference_counter.remove_submitted_task_references(deps)
            # object-ready + task-finished in ONE scheduler wakeup
            self.scheduler.notify_batch(
                ready_oids,
                [(exec_task_id, pending.node_index, spec.resources)])
            self.placement_groups.poke()
            # resubmit AFTER the finished notification so the scheduler
            # releases this execution's slot before seeing the retry
            if retry_task is not None:
                self._submit_retry(retry_task)

    # serializes thread-mode env'd tasks: sys.path / sys.modules are
    # process-global, and two concurrent tasks with DIFFERENT
    # working_dirs would resolve each other's imports (env_vars gets a
    # depth-counted push/pop; import visibility cannot be layered the
    # same way, so env'd tasks take turns — process workers are the
    # isolated path, as in the reference)
    _env_serial_lock = threading.Lock()
    _env_lock_owner: Optional[int] = None  # thread ident holding the lock

    def _enter_runtime_env(self, runtime_env: Optional[dict]):
        """Thread-mode env application: working_dir extraction +
        pip-venv site-packages on sys.path for the task's duration
        (no chdir — one process cwd is shared across thread workers,
        same documented caveat as thread-mode env_vars)."""
        if not runtime_env or not (runtime_env.get("working_dir_pkg")
                                   or runtime_env.get("pip")):
            return None
        from ray_tpu._private import runtime_envs as rte

        Worker._env_serial_lock.acquire()
        Worker._env_lock_owner = threading.get_ident()
        try:
            mgr = rte.get_manager()
            wd_path = None
            pkg = runtime_env.get("working_dir_pkg")
            if pkg:
                wd_path = mgr.ensure_working_dir(
                    pkg, lambda: self.gcs.kv_get(rte.kv_key(pkg)))
            sp = None
            if runtime_env.get("pip"):
                sp = mgr.ensure_pip(list(runtime_env["pip"]))
            ctx = rte.applied_env(wd_path, sp, use_cwd=False)
            ctx.__enter__()
        except BaseException:
            Worker._env_lock_owner = None
            Worker._env_serial_lock.release()
            raise

        class _LockedEnv:
            """applied_env + the serialization lock, released together."""

            def __exit__(self, *exc):
                try:
                    ctx.__exit__(*exc)
                finally:
                    Worker._env_lock_owner = None
                    Worker._env_serial_lock.release()
                return False

        return _LockedEnv()

    def _resolve_args(self, spec: TaskSpec):
        """Replace top-level ObjectRefs by values (reference semantics: only
        top-level args are awaited/inlined; nested refs pass through).

        Returns (args, kwargs, dep_error, requeue_deps): requeue_deps
        lists LOST deps now under lineage reconstruction — the caller
        re-queues the task to wait for them instead of blocking an
        executor thread (which the reconstruction itself may need)."""
        if not spec.args and not spec.kwargs:
            return (), {}, None, None
        dep_error = None
        requeue_deps: List[ObjectID] = []

        def resolve(v):
            nonlocal dep_error
            if isinstance(v, ObjectRef):
                oid = v.object_id()
                entry = self.memory_store.get_entry(oid)
                if entry is None:
                    # scheduler guaranteed readiness, so the object was
                    # LOST since: reconstruct from lineage
                    if self.object_recovery.maybe_recover(oid):
                        requeue_deps.append(oid)
                        return None
                    # unrecoverable: a tombstoned loss stored its error
                    entry = self.memory_store.get_entry(oid)
                if entry is None:
                    dep_error = rex.ObjectLostError(v.hex())
                    return None
                if entry.is_exception:
                    dep_error = entry.value
                    return None
                return self._entry_value(oid, entry)
            return v

        args = tuple(resolve(a) for a in spec.args)
        kwargs = {k: resolve(v) for k, v in spec.kwargs.items()}
        return args, kwargs, dep_error, requeue_deps

    def _store_returns(self, spec: TaskSpec, return_ids: List[ObjectID],
                       result) -> List[ObjectID]:
        """Store results; returns the stored oids — the CALLER delivers
        the object-ready notifications (batched with task-finished)."""
        if spec.num_returns == 1:
            values = [result]
        else:
            values = list(result) if result is not None else []
            if len(values) != spec.num_returns:
                err = ValueError(
                    f"task {spec.name} declared num_returns={spec.num_returns} "
                    f"but returned {len(values)} values")
                self._store_error(spec, return_ids, err)
                return []
        for oid, v in zip(return_ids, values):
            self.memory_store.put(oid, v)
        self.task_manager.complete(spec.task_id)
        return return_ids

    def _handle_task_failure(self, spec: TaskSpec, return_ids,
                             exc: BaseException) -> Optional[PendingTask]:
        """Store the error, or build the retry task for the caller to submit
        once this execution's finished-notification has gone out."""
        if self.task_manager.should_retry(spec, exc):
            spec.attempt_number += 1
            old_id = spec.task_id
            spec.task_id = self.next_task_id()  # retries get a fresh attempt id
            self.task_manager.num_retries += 1
            logger.warning("retrying task %s (attempt %d/%d): %s", spec.name,
                           spec.attempt_number, spec.max_retries, exc)
            msg = str(exc)
            if "(chaos" in msg:
                # an injected fault reached the retry machinery: count
                # the recovery against its site
                self._chaos.note_recovery(
                    "worker" if "chaos worker kill" in msg else "task")
            # resubmit under the ORIGINAL return ids
            spec._retry_return_ids = return_ids  # type: ignore[attr-defined]
            spec._backoff = True  # failure retry: _submit_retry delays it
            deps = _top_level_deps(spec.args, spec.kwargs)
            self.task_manager.rekey_pending(old_id, spec, deps)
            if self.task_events is not None:
                # old attempt -> failed ring (flagged retried); the new
                # attempt id opens its own record
                self.task_events.record_retry(
                    old_id, _task_error_type(exc), spec)
            if self.trace_plane is not None:
                # same logical span (spec.trace_ctx is untouched by the
                # in-place retry): the attempts link under one span
                self.trace_plane.record_retry(
                    old_id, _task_error_type(exc), spec)
            unresolved = [d for d in deps if not self.memory_store.contains(d)]
            return PendingTask(spec=spec, deps=unresolved,
                               execute=_noop_exec)
        if isinstance(exc, rex.TaskCancelledError):
            self._store_error(spec, return_ids, exc)
        elif isinstance(exc, rex.TaskTimeoutError):
            # exhausted deadline retries: one summary error chaining the
            # last per-attempt timeout (`raise ... from last_err`)
            final = rex.TaskTimeoutError(
                f"task {spec.name} timed out after {spec.attempt_number + 1} "
                f"attempt(s) of {spec.timeout_s}s each",
                task_id=spec.task_id, timeout_s=spec.timeout_s)
            final.__cause__ = exc
            self._store_error(spec, return_ids, final)
        else:
            tb = "".join(traceback.format_exception(type(exc), exc,
                                                    exc.__traceback__))
            err = rex.TaskError(spec.name, exc, tb)
            err.__cause__ = exc  # retry exhaustion chains the last failure
            self._store_error(spec, return_ids, err)
        return None

    def _store_error(self, spec: TaskSpec, return_ids, exc: BaseException):
        if self.task_events is not None:
            # terminal failure (retries, if any, were exhausted)
            self.task_events.record_failed(
                spec.task_id, _task_error_type(exc), name=spec.name,
                attempt=spec.attempt_number)
        if self.trace_plane is not None:
            self.trace_plane.record_failed(spec.task_id,
                                           _task_error_type(exc))
        for oid in return_ids:
            self.memory_store.put(oid, exc, is_exception=True)
            self.scheduler.notify_object_ready(oid)
        self.task_manager.complete(spec.task_id)

    def _maybe_inject_failure(self):
        """Thread-mode ``task`` injection site. The controller also
        honors the live testing_inject_task_failure_prob knob."""
        fault = self._chaos.poll("task")
        if fault is None:
            return
        if fault["kind"] == "hang":
            time.sleep(fault.get("hang_s", 0.2))
            return
        raise rex.WorkerCrashedError("injected failure (chaos)")

    # ------------------------------------------------------------------
    # Supervision: retry backoff + per-task deadlines
    # ------------------------------------------------------------------
    def _claim_task_completion(self, exec_task_id: TaskID):
        """Atomically end an attempt's cancellable window and return the
        flag it finished under: "timeout" means the deadline watcher
        already failed/retried the attempt (suppress the zombie's
        results), True means cancel_task flipped it mid-run (store
        TaskCancelledError, never retry), False/None is a clean finish."""
        with self._running_lock:
            return self._running_tasks.pop(exec_task_id, None)

    def _submit_retry(self, retry_task: PendingTask) -> None:
        """Resubmit a failed attempt's retry after exponential backoff
        (base delay doubling per attempt, capped, with seeded jitter) so
        a flapping node is not hammered with immediate resubmissions.
        Dep-requeues (no attempt bump) resubmit immediately. Call AFTER
        the attempt's finished-notification, like scheduler.submit."""
        spec = retry_task.spec
        if not getattr(spec, "_backoff", False):
            self.scheduler.submit(retry_task)
            return
        spec._backoff = False
        base = GLOBAL_CONFIG.task_retry_delay_s
        delay = 0.0
        if base > 0.0:
            delay = min(base * (2 ** max(spec.attempt_number - 1, 0)),
                        GLOBAL_CONFIG.task_retry_max_delay_s)
            if GLOBAL_CONFIG.task_retry_jitter:
                delay *= self._chaos.backoff_jitter(spec.attempt_number,
                                                    spec.name)
        # per-attempt delays kept on the spec so tests can assert growth
        delays = getattr(spec, "_retry_delays", None)
        if delays is None:
            delays = spec._retry_delays = []  # type: ignore[attr-defined]
        delays.append(delay)
        if delay <= 0.0:
            self._submit_retry_now(retry_task)
            return
        t = threading.Timer(delay, self._submit_retry_now, (retry_task,))
        t.daemon = True
        t.start()

    def _submit_retry_now(self, retry_task: PendingTask) -> None:
        if not self.alive:
            return
        spec = retry_task.spec
        try:
            if spec.timeout_s:
                self._register_deadline(spec)
            self.scheduler.submit(retry_task)
        except Exception:
            logger.exception("retry submission failed for %s", spec.name)

    def _register_deadline(self, spec: TaskSpec) -> None:
        """Arm the per-attempt deadline for spec's CURRENT task id; the
        watcher thread starts lazily with the first armed deadline."""
        if not spec.timeout_s or spec.timeout_s <= 0:
            return
        with self._deadline_cv:
            heapq.heappush(self._deadline_heap,
                           (time.monotonic() + spec.timeout_s,
                            self._deadline_seq.next(), spec.task_id, spec))
            if self._deadline_thread is None:
                self._deadline_thread = threading.Thread(
                    target=self._deadline_loop, daemon=True,
                    name="ray_tpu_deadline")
                self._deadline_thread.start()
            self._deadline_cv.notify()

    def _deadline_loop(self) -> None:
        while self.alive:
            with self._deadline_cv:
                if not self._deadline_heap:
                    self._deadline_cv.wait(timeout=0.5)
                    continue
                now = time.monotonic()
                due_at = self._deadline_heap[0][0]
                if due_at > now:
                    self._deadline_cv.wait(
                        timeout=min(due_at - now, 0.5))
                    continue
                _, _, tid, spec = heapq.heappop(self._deadline_heap)
            try:
                self._on_task_deadline(spec, tid)
            except Exception:
                logger.exception("deadline enforcement failed for %s",
                                 spec.name)

    def _on_task_deadline(self, spec: TaskSpec, tid: TaskID) -> None:
        """One expired deadline. ``tid`` is the attempt the deadline was
        armed for; a later attempt id on the spec means that attempt
        already resolved (each retry re-arms its own deadline)."""
        if spec.task_id is not tid and spec.task_id != tid:
            return
        if self.task_manager.get_pending_spec(tid) is None:
            return  # attempt completed under the wire
        err = rex.TaskTimeoutError(
            f"task {spec.name} exceeded its {spec.timeout_s}s deadline "
            f"(attempt {spec.attempt_number + 1})",
            task_id=tid, timeout_s=spec.timeout_s)
        return_ids = (getattr(spec, "_retry_return_ids", None)
                      or spec.return_ids())
        # (a) still queued in the scheduler: pull it out (no slot held,
        #     so no finished-notification is owed)
        if self.scheduler.cancel(tid):
            retry = self._handle_task_failure(spec, return_ids, err)
            if retry is not None:
                self._submit_retry(retry)
            return
        # (b) leased to a process/remote pool: force-kill the attempt
        #     there, classified as a timeout (retriable)
        pools = list(self._node_pools.values())
        if self.process_pool is not None and self.process_pool not in pools:
            pools.append(self.process_pool)
        for pool in pools:
            cancel_to = getattr(pool, "cancel_for_timeout", None)
            if cancel_to is not None and cancel_to(tid):
                return  # pool failure path raises TaskTimeoutError
        # (c) thread mode: running (flag the attempt as timed out and
        #     fail it now — the zombie thread's results are suppressed)
        #     or executor-queued (timed out at execution start)
        synthesize = False
        with self._running_lock:
            flag = self._running_tasks.get(tid)
            if flag is False:
                self._running_tasks[tid] = "timeout"
                synthesize = True
            elif flag is None and spec.task_id == tid \
                    and self.task_manager.get_pending_spec(tid) is not None:
                self._pretimeout.add(tid)
        if synthesize:
            retry = self._handle_task_failure(spec, return_ids, err)
            if retry is not None:
                self._submit_retry(retry)

    # ------------------------------------------------------------------
    # Supervision: QoS preemption (config.qos)
    # ------------------------------------------------------------------
    def _qos_loop(self) -> None:
        """Preemption monitor: once the plane reports a starved higher
        tier (past preempt_grace_s), kill the lowest-tier running
        victim through the same paths the deadline watcher uses — the
        failure is a synthetic worker death, so the victim retries with
        a bumped attempt under its original return ids (journaled
        lease, exactly-once), never a double execution."""
        while self.alive:
            time.sleep(0.05)
            plane = self.qos_plane
            if plane is None or not self.alive:
                continue
            victim = plane.check_preempt(time.monotonic())
            if victim is None:
                continue
            tid, tenant, tier, starved_tier = victim
            try:
                if self._preempt_task(tid, tier, starved_tier):
                    plane.note_preempted(tenant, tier)
                    self.note_two_level("preempts")
            except Exception:
                logger.exception("preemption failed for task %s",
                                 tid.hex()[:16])

    def _preempt_task(self, tid: TaskID, tier: int,
                      starved_tier: int) -> bool:
        """Kill one running attempt to make room for a starved higher
        tier. Returns True when a kill was delivered (the retry is
        owned by whichever failure path runs it)."""
        spec = self.task_manager.get_pending_spec(tid)
        if spec is None or spec.task_id != tid:
            return False  # attempt resolved (or retried) under the wire
        # the preemption contract: a victim is re-queued, never
        # terminally failed — grant the synthetic death an attempt if
        # the victim had none left
        if spec.attempt_number >= spec.max_retries:
            spec.max_retries = spec.attempt_number + 1
        err = rex.WorkerCrashedError(
            f"task {spec.name} preempted by tier-{starved_tier} work "
            f"(was running at tier {tier}); attempt will retry")
        return_ids = (getattr(spec, "_retry_return_ids", None)
                      or spec.return_ids())
        # (a) leased to a process/remote pool: force-kill the attempt
        #     there — the pool failure path classifies it retriable
        pools = list(self._node_pools.values())
        if self.process_pool is not None and self.process_pool not in pools:
            pools.append(self.process_pool)
        for pool in pools:
            c = getattr(pool, "cancel_for_preemption", None)
            if c is not None and c(tid):
                return True
        # (b) thread mode: flag the attempt as supervisor-failed (the
        #     cooperative zombie's results are suppressed, exactly like
        #     a deadline kill) and synthesize the worker death
        synthesize = False
        with self._running_lock:
            if self._running_tasks.get(tid) is False:
                self._running_tasks[tid] = "timeout"
                synthesize = True
        if synthesize:
            retry = self._handle_task_failure(spec, return_ids, err)
            if retry is not None:
                self._submit_retry(retry)
            return True
        return False

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------
    def defer_unref(self, object_id: ObjectID) -> None:
        self._unref_queue.append(object_id)
        self._unref_event.set()

    def _unref_loop(self) -> None:
        while self.alive:
            self._unref_event.wait(timeout=0.5)
            self._unref_event.clear()
            while self._unref_queue:
                try:
                    oid = self._unref_queue.popleft()
                except IndexError:
                    break
                try:
                    self.reference_counter.remove_local_reference(oid)
                except Exception:
                    logger.exception("unref failed for %s", oid)

    def free_objects(self, refs: Sequence[ObjectRef]) -> None:
        """Drop stored values WITHOUT touching reference counts — the
        analog of ray._private.internal_api.free (and of losing the
        objects to eviction/node death). A later get() reconstructs them
        from lineage if their producing tasks are still recoverable."""
        for r in refs:
            oid = r.object_id()
            self.object_recovery.note_freed(oid)
            self.memory_store.delete([oid])
            if self.shm_store is not None:
                self.shm_store.free_object(oid)
            self._free_remote_copy(oid)

    def _free_remote_copy(self, object_id: ObjectID) -> None:
        # EVERY copy frees — staged secondaries pin peer arenas too
        for node in self.gcs.object_locations_pop(object_id):
            pool = self._node_pools.get(node)
            if pool is not None and getattr(pool, "is_remote", False):
                pool.free_remote([object_id])

    def _on_object_out_of_scope(self, object_id: ObjectID) -> None:
        # Deferred batch free: __del__-driven releases arrive one at a
        # time (e.g. 50k refs dying after a batched get), and freeing
        # per object pays store/lineage lock acquisitions per oid. A
        # zero-refcount object can never regain references, so deferral
        # is safe; the size threshold plus drains at the API entry
        # points bound how long reclaim can lag.
        q = self._oos_q
        q.append(object_id)
        if len(q) >= 128 or (self.shm_store is not None
                             and self.shm_store.contains(object_id)) \
                or (self._has_remote_nodes
                    and self.gcs.object_location_get(object_id)
                    is not None):
            # arena-resident and REMOTE-resident objects are the
            # memory that matters — reclaim those immediately (a
            # remote copy pins another node's arena); only small
            # in-process entries ride the deferred batch. The GCS
            # location lookup is gated on a REMOTE pool existing:
            # single-node runs — thread OR process mode, the common
            # case and the bench — must not pay a GCS lock round trip
            # per dying ref
            self._drain_out_of_scope()

    def _drain_out_of_scope(self) -> None:
        q = self._oos_q
        if not q:
            return
        batch: List[ObjectID] = []
        while True:
            try:
                batch.append(q.popleft())
            except IndexError:
                break
        if not batch:
            return
        self.memory_store.delete(batch)
        if self.shm_store is not None:
            for oid in batch:
                self.shm_store.free_object(oid)
        for oid in batch:
            self._free_remote_copy(oid)
        self.task_manager.evict_lineage_batch(batch)

    def shutdown(self) -> None:
        self.alive = False
        with self._deadline_cv:
            self._deadline_cv.notify_all()  # release the watcher promptly
        if self.log_monitor is not None:
            # stop BEFORE the pools die: the final sweep re-emits any
            # trailing captured output while the files still matter
            self.log_monitor.stop()
        if self._gcs_log_handler is not None:
            import logging as _logging
            _logging.getLogger("ray_tpu").removeHandler(
                self._gcs_log_handler)
            try:
                self._gcs_log_handler.close()
            except Exception:
                pass
            self._gcs_log_handler = None
        from ray_tpu._private import log_plane
        if log_plane.get_session_log_dir() == self.session_log_dir:
            log_plane.set_session_log_dir(None)
        self._drain_out_of_scope()
        self.placement_groups.shutdown()
        with self._actors_lock:
            actors = list(self.actors.values())
        for rt in actors:
            try:
                rt.stop(no_restart=True)
            except Exception:
                pass
        self.scheduler.shutdown()
        self.gcs.shutdown()
        self.memory_monitor.shutdown()
        if self.profile_plane is not None:
            self.profile_plane.shutdown()
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
        for row, pool in list(self._node_pools.items()):
            if pool is not self.process_pool:
                pool.shutdown()
        if self.process_pool is not None:
            self.process_pool.shutdown()
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self.client_server is not None:
            self.client_server.shutdown()
        if self._head_server is not None:
            self._head_server.close()
        if runtime_sanitizer._ENABLED:
            # lock-witness diff + leak ledgers, while the refcount table
            # still distinguishes live objects from leaked segments
            runtime_sanitizer.report_at_shutdown(
                self.reference_counter.snapshot())
        if self.shm_store is not None:
            self.shm_store.shutdown()


def _top_level_deps(args, kwargs) -> List[ObjectID]:
    deps = [a.object_id() for a in args if isinstance(a, ObjectRef)]
    if kwargs:
        deps.extend(v.object_id() for v in kwargs.values()
                    if isinstance(v, ObjectRef))
    return deps


def _likely_large(value: Any) -> bool:
    """Cheap size probe deciding whether a put should try the shm path
    (avoids serializing every small put twice). Arrays/bytes report real
    sizes; other objects are assumed small and stay in the memory store."""
    import numpy as _np
    if isinstance(value, _np.ndarray):
        return value.nbytes > GLOBAL_CONFIG.inline_object_max_bytes
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value) > GLOBAL_CONFIG.inline_object_max_bytes
    t = type(value)
    if ((t.__module__ or "").split(".")[0] == "pyarrow"
            and hasattr(value, "nbytes")):
        # Arrow tables/arrays: data-plane blocks — workers must read
        # them zero-copy from the arena, not over the task pipe
        return value.nbytes > GLOBAL_CONFIG.inline_object_max_bytes
    try:
        import jax
        if isinstance(value, jax.Array):
            return value.nbytes > GLOBAL_CONFIG.inline_object_max_bytes
    except Exception:
        pass
    return False


def _detect_tpu_count() -> float:
    # a cpu-pinned run (tests, bench subprocesses) must NEVER touch the
    # accelerator plugin: jax.devices() initializes it, and a degraded
    # chip tunnel then hangs every ray_tpu.init() indefinitely. The
    # env var alone is unreliable — the axon plugin rewrites it at jax
    # import (see tests/conftest.py) — so also consult jax.config when
    # jax is already imported
    import sys as _sys

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return 0.0
    if "jax" in _sys.modules:
        try:
            cfg = _sys.modules["jax"].config.jax_platforms
            if cfg and str(cfg).strip().lower() == "cpu":
                return 0.0
        except Exception:
            pass
    try:
        import jax
        return float(len([d for d in jax.devices()
                          if d.platform not in ("cpu",)]))
    except Exception:
        return 0.0


# runtime_env env_vars in THREAD mode share one process environment.
# Depth-counted apply/restore: concurrent env-bearing tasks may observe
# each other mid-flight (documented caveat), but completion always
# restores the TRUE pre-task value — naive save/restore interleaving
# would leak a task's value into the process forever.
_env_state_lock = threading.Lock()
_env_depth: Dict[str, Tuple[int, Optional[str]]] = {}


def env_vars_push(env_vars: Dict[str, str]) -> None:
    with _env_state_lock:
        for k, v in env_vars.items():
            depth, orig = _env_depth.get(k, (0, os.environ.get(k)))
            _env_depth[k] = (depth + 1, orig)
            os.environ[k] = v


def env_vars_pop(env_vars: Dict[str, str]) -> None:
    with _env_state_lock:
        for k in env_vars:
            entry = _env_depth.pop(k, None)
            if entry is None:
                continue
            depth, orig = entry
            if depth > 1:
                _env_depth[k] = (depth - 1, orig)
            elif orig is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = orig


def _async_raise_in_task(task_id: TaskID) -> None:
    """Best-effort forced cancellation in thread mode."""
    # thread-level force-kill is unsafe; cooperative cancellation only.
    logger.warning("force cancel requested for %s; thread workers support "
                   "cooperative cancellation only", task_id)


# ----------------------------------------------------------------------
# Module-level API used by ray_tpu/__init__.py
# ----------------------------------------------------------------------

def init(num_cpus: Optional[float] = None, num_workers: Optional[int] = None,
         scheduler: Optional[str] = None, ignore_reinit_error: bool = False,
         resources: Optional[Dict[str, float]] = None,
         address: Optional[str] = None,
         log_to_driver: bool = True,
         _system_config: Optional[dict] = None, **kwargs) -> "Worker":
    global global_worker
    with _init_lock:
        if global_worker is not None and global_worker.alive:
            if ignore_reinit_error:
                return global_worker
            raise RuntimeError("ray_tpu.init() called twice; pass "
                               "ignore_reinit_error=True to allow")
        if address is not None and address.startswith("ray://"):
            # client mode: this process becomes a THIN CLIENT of a
            # running head (reference: ray client, python/ray/util/client)
            from ray_tpu._private.client import (ClientWorker,
                                                 parse_client_address)
            host, port, key = parse_client_address(address)
            if key is None:
                raise ValueError(
                    "client address needs the head's key: use the "
                    "ray://host:port?key=... string printed by "
                    "`python -m ray_tpu start --head`")
            global_worker = ClientWorker(host, port, key)  # type: ignore
            return global_worker  # type: ignore[return-value]
        if _system_config:
            GLOBAL_CONFIG.unfreeze()
            GLOBAL_CONFIG.apply_system_config(_system_config)
        # max_direct_call_object_size is the reference API's name for
        # inline_object_max_bytes: an override of the alias (env or
        # _system_config) flows into the real knob, unless the real
        # knob was itself overridden — then the specific name wins
        alias = GLOBAL_CONFIG.entry("max_direct_call_object_size")
        inline = GLOBAL_CONFIG.entry("inline_object_max_bytes")
        if alias.value != alias.default and inline.value == inline.default:
            inline.value = int(alias.value)
        # Two separate knobs (previously conflated): ``scheduler`` picks the
        # scheduler CLASS (tensor = device-array north star, the default;
        # event = per-event oracle); ``sched_backend`` picks the tensor
        # scheduler's TICK backend (auto|jax|numpy).
        scheduler_factory = None
        impl = scheduler or GLOBAL_CONFIG.scheduler
        if impl in ("tensor", "jax"):  # "jax" kept as a legacy alias
            from ray_tpu._private.scheduler.tensor import TensorScheduler
            scheduler_factory = (
                lambda nodes, dispatch, contains:
                TensorScheduler(nodes, dispatch, contains))
        elif impl != "event":
            raise ValueError(f"unknown scheduler {impl!r}: tensor | event")
        GLOBAL_CONFIG.freeze()
        global_worker = Worker(num_cpus=num_cpus, num_workers=num_workers,
                               scheduler_factory=scheduler_factory,
                               resources=resources,
                               log_to_driver=log_to_driver)
        if GLOBAL_CONFIG.gc_tuning:
            # see the config knob's docstring (including the freeze
            # caveat); shutdown() undoes both, restoring the HOST
            # program's thresholds, not CPython defaults
            import gc
            global _gc_tuned, _gc_saved_threshold
            _gc_saved_threshold = gc.get_threshold()
            gc.collect()
            gc.freeze()
            gc.set_threshold(20_000, 20, 20)
            _gc_tuned = True
        return global_worker


def shutdown() -> None:
    global global_worker, _gc_tuned
    with _init_lock:
        if global_worker is not None:
            global_worker.shutdown()
            global_worker = None
        if _gc_tuned:
            import gc
            gc.unfreeze()
            gc.set_threshold(*_gc_saved_threshold)
            _gc_tuned = False
        GLOBAL_CONFIG.unfreeze()
        # _system_config is scoped to one init/shutdown cycle; a leaked
        # worker_mode=process would silently re-route the next runtime
        GLOBAL_CONFIG.reset()
        # chaos schedules are scoped the same way: an armed plan must
        # not leak into the next runtime's fault decisions
        _chaos_controller().reset()


def is_initialized() -> bool:
    return global_worker is not None and global_worker.alive


def get_worker(auto_init: bool = True) -> Worker:
    if global_worker is None or not global_worker.alive:
        if not auto_init:
            raise RuntimeError("ray_tpu.init() has not been called")
        init()
    return global_worker  # type: ignore
