"""Seeded chaos plane: scheduleable fault injection with named sites.

Reference: the reference repo exercises its recovery paths with ad-hoc
helpers (``testing_inject_task_failure_prob``, chaos kill in
cluster_utils); real chaos frameworks (Jepsen, ChaosMonkey) make fault
schedules *deterministic* so a failing run can be replayed bit-for-bit.
This module is that layer for ray_tpu: a process-wide
:class:`FaultController` owns every injection decision, driven by a
seeded :class:`FaultPlan` (fire fault KIND at the Nth arrival of SITE)
plus optional per-site probabilities whose draws are derived from
``(seed, site, arrival)`` — so two runs with the same seed inject the
identical fault sequence regardless of thread interleaving.

Injection sites threaded through the runtime (see ``SITES``):

========== ==================== =====================================
site       kinds                hooked where
========== ==================== =====================================
task       exception, hang      thread: Worker._maybe_inject_failure;
                                process: per-payload at _build_payload
worker     kill                 ProcessWorkerPool / RemoteNodePool
                                SIGKILL the assigned worker
link       delay, drop          ProcessWorkerPool pipe send and
                                RemoteNodePool._send_daemon
transfer   truncate             RemoteNodePool.fetch_object (wire
                                corruption of object bytes)
sched_tick slow                 Worker dispatch path (slow node)
heartbeat  drop                 GcsService health loop (node stays
                                connected but its heartbeat is lost)
head       kill, restart, flap  GcsService health loop: ``flap``
                                severs every remote daemon link
                                (exercising outbox replay + rejoin
                                re-attach without killing anyone);
                                ``kill`` SIGKILLs the head process
                                itself; ``restart`` is a marker for
                                external harnesses (bench/soak
                                drivers kill + relaunch the head
                                subprocess at the seeded arrival)
node       kill, restart, flap  GcsService health loop: ``kill``
                                SIGKILLs a remote node's daemon
                                with its whole worker tree (the
                                machine-death drill — the head-side
                                node-death reconciler must retry or
                                fail its adopted local leases, purge
                                ghost gossip views, and broadcast
                                route invalidation); ``flap`` severs
                                just that node's daemon link;
                                ``restart`` is a marker for external
                                harnesses (kill + relaunch the node
                                process). Params: ``node`` selects
                                the victim scheduler row (default:
                                the lowest-index alive remote node)
peer_link  delay, drop, sever   NodeDaemon peer actor lane (p2p
                                actor calls): ``delay`` stalls the
                                frame, ``drop`` loses the call
                                (immediate head fallback), ``sever``
                                kills the lane socket mid-flight so
                                every in-flight call on it falls
                                back to the head path. Polled on
                                DAEMON processes: the head mirrors
                                its armed plan to daemons through
                                the resview push, and daemon-fired
                                injections ride the outbox back as
                                ("fault", entry) reports
========== ==================== =====================================

The public surface is :mod:`ray_tpu.chaos`; ``state.list_faults()``
returns the injection log and ``_private/metrics.py`` exports the
injected/recovered counters.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

SITES: Tuple[str, ...] = (
    "task", "worker", "link", "transfer", "sched_tick", "heartbeat",
    "head", "node", "peer_link")

_SITE_KINDS: Dict[str, Tuple[str, ...]] = {
    "task": ("exception", "hang"),
    "worker": ("kill",),
    "link": ("delay", "drop"),
    "transfer": ("truncate",),
    "sched_tick": ("slow",),
    "heartbeat": ("drop",),
    "head": ("kill", "restart", "flap"),
    "node": ("kill", "restart", "flap"),
    "peer_link": ("delay", "drop", "sever"),
}

# default parameters for kinds that need one; overridable per plan entry
# or per set_probability call
_DEFAULT_PARAMS: Dict[str, Dict[str, float]] = {
    "hang": {"hang_s": 0.2},
    "delay": {"delay_s": 0.05},
    "slow": {"delay_s": 0.05},
    "truncate": {"keep_fraction": 0.5},
}


class FaultPlan:
    """A deterministic fault schedule: ``(site, when, kind[, params])``
    entries, where ``when`` is the 0-based arrival index at ``site``
    (the Nth time the runtime consults the controller for that site).
    The seed drives probability draws and retry-backoff jitter; the
    scheduled entries themselves are exact."""

    def __init__(self, seed: int,
                 faults: Iterable[Sequence[Any]] = ()):
        self.seed = int(seed)
        self.faults: List[Tuple[str, int, str, Dict[str, Any]]] = []
        for entry in faults:
            site, when, kind = entry[0], int(entry[1]), entry[2]
            params = dict(entry[3]) if len(entry) > 3 else {}
            if site not in _SITE_KINDS:
                raise ValueError(f"unknown fault site {site!r}; "
                                 f"sites: {sorted(_SITE_KINDS)}")
            if kind not in _SITE_KINDS[site]:
                raise ValueError(
                    f"site {site!r} supports kinds {_SITE_KINDS[site]}, "
                    f"got {kind!r}")
            self.faults.append((site, when, kind, params))

    def __repr__(self):
        return (f"FaultPlan(seed={self.seed}, "
                f"faults={[(s, w, k) for s, w, k, _ in self.faults]})")


class FaultController:
    """Process-wide injection-decision owner. All runtime hooks call
    :meth:`poll` (near-zero cost while disarmed: one attribute read)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed = False          # fast-path gate, read without lock
        self._seed = 0
        self._plan: Dict[Tuple[str, int], Tuple[str, Dict[str, Any]]] = {}
        self._probs: Dict[str, Tuple[float, Dict[str, Any]]] = {}
        self._arrivals: Dict[str, int] = {}
        self._log: List[Dict[str, Any]] = []
        self._injected: Dict[str, int] = {}
        self._recovered: Dict[str, int] = {}
        self._cfg_entry = None       # live testing_inject_task_failure_prob

    # -- configuration ------------------------------------------------------
    def arm(self, plan: FaultPlan) -> None:
        """Install a plan (replaces any previous schedule; counters and
        the log reset so ``list_faults()`` describes exactly this run)."""
        with self._lock:
            self._seed = plan.seed
            self._plan = {(s, w): (k, p) for s, w, k, p in plan.faults}
            self._arrivals = {}
            self._log = []
            self._injected = {}
            self._recovered = {}
            self._armed = True

    def set_probability(self, site: str, prob: float, **params: Any) -> None:
        """Probabilistic injection at ``site`` (seeded: the draw for the
        Nth arrival is a pure function of (seed, site, N))."""
        if site not in _SITE_KINDS:
            raise ValueError(f"unknown fault site {site!r}")
        with self._lock:
            if prob <= 0.0:
                self._probs.pop(site, None)
            else:
                self._probs[site] = (float(prob), params)
            self._armed = bool(self._plan or self._probs)

    def plan_snapshot(self) -> Optional[Dict[str, Any]]:
        """Picklable image of the armed schedule, or None when
        disarmed. Daemons mirror the head's plan from this (resview
        push) so seeded faults fire at deterministic arrivals on the
        process that actually hosts the site (e.g. ``peer_link``)."""
        with self._lock:
            if not self._armed:
                return None
            return {
                "seed": self._seed,
                "faults": [(s, w, k, dict(p))
                           for (s, w), (k, p) in sorted(self._plan.items())],
                "probs": {s: (p, dict(params))
                          for s, (p, params) in self._probs.items()},
            }

    def arm_snapshot(self, snap: Optional[Dict[str, Any]]) -> None:
        """Install (or, with None, disarm) a schedule mirrored from
        another process's :meth:`plan_snapshot`."""
        if snap is None:
            self.disarm()
            return
        self.arm(FaultPlan(snap.get("seed", 0), snap.get("faults", ())))
        for site, (p, params) in (snap.get("probs") or {}).items():
            self.set_probability(site, p, **params)

    def note_remote(self, entry: Dict[str, Any]) -> None:
        """Record an injection that FIRED on another process (a daemon
        reported it over the outbox): it joins this controller's log
        and counters so ``list_faults()``/metrics stay cluster-wide."""
        with self._lock:
            site = entry.get("site", "?")
            self._injected[site] = self._injected.get(site, 0) + 1
            e = dict(entry)
            e["seq"] = len(self._log)
            self._log.append(e)

    def disarm(self) -> None:
        """Stop injecting; the log and counters survive for inspection."""
        with self._lock:
            self._armed = False
            self._plan = {}
            self._probs = {}

    def reset(self) -> None:
        """Full reset (called at runtime shutdown)."""
        with self._lock:
            self._armed = False
            self._seed = 0
            self._plan = {}
            self._probs = {}
            self._arrivals = {}
            self._log = []
            self._injected = {}
            self._recovered = {}

    # -- the hot hook -------------------------------------------------------
    def armed(self) -> bool:
        """Lock-free fast-path gate (same read poll() itself leads
        with): callers that poll non-``task`` sites once per leased
        task may skip the whole loop when no plan is armed — arrival
        counting only happens while armed, so the skip is invisible to
        any plan's ``when`` coordinates."""
        return self._armed

    def poll(self, site: str, **context: Any) -> Optional[Dict[str, Any]]:
        """Consult the controller at an injection site. Returns a fault
        descriptor ``{"kind": ..., <params>}`` or None. Counts one
        arrival at ``site`` whenever the controller is armed (arrival
        indices are the plan's ``when`` coordinates).

        The ``task`` site additionally honors the live
        ``testing_inject_task_failure_prob`` config knob, re-read per
        task (it used to be baked into ProcessWorkerPool at
        construction).
        """
        if not self._armed:
            if site == "task":
                return self._poll_config_prob(context)
            return None
        with self._lock:
            n = self._arrivals.get(site, 0)
            self._arrivals[site] = n + 1
            hit = self._plan.get((site, n))
            if hit is not None:
                kind, params = hit
                return self._fire_locked(site, kind, n, params, context)
            prob = self._probs.get(site)
            if prob is not None:
                p, params = prob
                if self._draw(site, n) < p:
                    kind = params.get("kind", _SITE_KINDS[site][0])
                    return self._fire_locked(site, kind, n, params, context)
        if site == "task":
            return self._poll_config_prob(context)
        return None

    def note_recovery(self, site: str, **context: Any) -> None:
        """Record that the runtime recovered from an injected fault
        (retry scheduled, node respawned elsewhere, ...)."""
        with self._lock:
            self._recovered[site] = self._recovered.get(site, 0) + 1

    # -- observability ------------------------------------------------------
    def list_faults(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._log]

    def counters(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "injected": dict(self._injected),
                "recovered": dict(self._recovered),
                "injected_total": sum(self._injected.values()),
                "recovered_total": sum(self._recovered.values()),
            }

    @property
    def seed(self) -> int:
        return self._seed

    # -- internals ----------------------------------------------------------
    def _fire_locked(self, site: str, kind: str, when: int,
                     params: Dict[str, Any],
                     context: Dict[str, Any]) -> Dict[str, Any]:
        fault = dict(_DEFAULT_PARAMS.get(kind, {}))
        fault.update({k: v for k, v in params.items() if k != "kind"})
        fault["kind"] = kind
        self._injected[site] = self._injected.get(site, 0) + 1
        self._log.append({
            "seq": len(self._log), "site": site, "kind": kind,
            "when": when, "context": dict(context),
        })
        return fault

    def _draw(self, site: str, arrival: int) -> float:
        # pure function of (seed, site, arrival): thread interleaving
        # across sites cannot perturb the sequence
        return random.Random(f"{self._seed}:{site}:{arrival}").random()

    def _poll_config_prob(self, context) -> Optional[Dict[str, Any]]:
        ent = self._cfg_entry
        if ent is None:
            from ray_tpu._private.config import GLOBAL_CONFIG
            ent = self._cfg_entry = GLOBAL_CONFIG.entry(
                "testing_inject_task_failure_prob")
        p = ent.value
        if p > 0.0 and random.random() < p:
            with self._lock:
                return self._fire_locked(
                    "task", "exception", self._arrivals.get("task", 0),
                    {}, dict(context))
        return None

    def backoff_jitter(self, attempt: int, task_key: str = "") -> float:
        """Deterministic jitter factor in [0.5, 1.0) for retry backoff,
        derived from the chaos seed so soak runs replay exactly."""
        return 0.5 + 0.5 * random.Random(
            f"{self._seed}:backoff:{task_key}:{attempt}").random()


_CONTROLLER = FaultController()


def get_controller() -> FaultController:
    return _CONTROLLER
