"""Pass 7 — closure-capture hygiene for remote task definitions.

Everything a ``@remote`` function closes over crosses
serialization.py BY VALUE on every submission (cloudpickle walks the
closure cells). Four capture shapes are flagged, all on remote defs
NESTED inside another function/method (top-level remote functions only
close over module globals, which pickle by reference):

- **self-capture**: the task body references ``self`` from an
  enclosing method — the whole instance (locks, sockets, caches and
  all) ships with every submission, and usually fails to pickle only
  in production, not in the unit test.
- **resource-capture**: a free variable bound in the enclosing scope
  to a lock/condition, ``open(...)`` handle, socket, or thread —
  process-local kernel state that is meaningless (or unpicklable) on
  the other side.
- **array-capture**: a free variable bound to a numpy/jax array
  constructor in the enclosing scope — the array is re-serialized into
  every task instead of being ``put()`` once and passed as a ref.
- **module-capture**: a free variable bound by a function-local
  ``import`` in the enclosing scope — cloudpickle serializes the
  module object itself rather than a by-reference stub.

A remote def is one decorated ``@remote`` / ``@ray_tpu.remote`` (with
or without options), or a nested def later passed to ``remote(...)``.
Free variables are loads not bound by the def's own params,
assignments, or imports.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.analysis._astutil import (iter_py_files,
                                                module_name, parse_file)

PASS = "closure_capture"

#: constructor attrs whose result is kernel/process-local state
_RESOURCE_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                       "BoundedSemaphore", "Event", "Thread", "socket",
                       "open", "Popen"}
#: attrs that build a (potentially large) array value
_ARRAY_FACTORIES = {"zeros", "ones", "empty", "full", "arange",
                    "linspace", "eye", "array", "asarray", "rand",
                    "randn", "random", "normal", "uniform"}


def _walk_local(fn: ast.AST):
    """ast.walk constrained to ``fn``'s own scope — nested defs are
    separate scopes (and are visited via _scopes on their own)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _call_name(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_remote_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = d.attr if isinstance(d, ast.Attribute) else (
            d.id if isinstance(d, ast.Name) else None)
        if name == "remote":
            return True
    return False


def _bound_names(fn: ast.FunctionDef) -> Set[str]:
    """Names the function binds itself: params, assignments, imports,
    comprehension targets, nested defs, for-targets, with-as."""
    bound: Set[str] = set()
    a = fn.args
    for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)):
        bound.add(arg.arg)
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            bound.add(sub.id)
        elif isinstance(sub, (ast.Import, ast.ImportFrom)):
            for alias in sub.names:
                bound.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)) and sub is not fn:
            bound.add(sub.name)
    return bound


def _free_vars(fn: ast.FunctionDef) -> Dict[str, int]:
    """Loaded names not bound by the def itself: name -> first line.

    Only the BODY is walked: decorators, annotations and defaults
    evaluate in the enclosing scope at def time — ``@ray_tpu.remote``
    itself is not a closure capture."""
    bound = _bound_names(fn)
    free: Dict[str, int] = {}
    for stmt in fn.body:
        for sub in ast.walk(stmt):
            if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                    and sub.id not in bound):
                free.setdefault(sub.id, sub.lineno)
    return free


class _EnclosingScope:
    """What the enclosing function binds each local name to."""

    def __init__(self, fn: ast.FunctionDef, is_method: bool):
        self.is_method = is_method
        #: name -> "resource" | "array" | "module"
        self.kinds: Dict[str, str] = {}
        for sub in _walk_local(fn):
            if isinstance(sub, ast.Assign):
                kind = self._value_kind(sub.value)
                if kind:
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            self.kinds[tgt.id] = kind
            elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                if isinstance(sub, ast.Import):
                    for alias in sub.names:
                        self.kinds[(alias.asname
                                    or alias.name).split(".")[0]] = "module"

    @staticmethod
    def _value_kind(value: ast.AST) -> Optional[str]:
        name = _call_name(value)
        if name in _RESOURCE_FACTORIES:
            return "resource"
        if name in _ARRAY_FACTORIES:
            return "array"
        return None


def _remote_defs_in(fn: ast.FunctionDef) -> List[ast.FunctionDef]:
    """Nested defs submitted remotely: decorated, or passed to remote()."""
    nested = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested.append(node)
            continue  # deeper defs belong to THIS nested def's scope
        if isinstance(node, ast.ClassDef):
            continue
        stack.extend(ast.iter_child_nodes(node))
    out = [n for n in nested if _is_remote_decorated(n)]
    wrapped: Set[str] = set()
    for sub in _walk_local(fn):
        if isinstance(sub, ast.Call) and _call_name(sub) == "remote":
            for a in sub.args:
                if isinstance(a, ast.Name):
                    wrapped.add(a.id)
    out.extend(n for n in nested
               if n.name in wrapped and not _is_remote_decorated(n))
    return out


def _scopes(tree: ast.Module):
    """Yield (qualname, fn, is_method) for every function with nesting
    context, so a remote def's ENCLOSING scope is known."""
    def walk(node, prefix, in_class):
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{sub.name}" if prefix else sub.name
                yield q, sub, in_class
                yield from walk(sub, q, False)
            elif isinstance(sub, ast.ClassDef):
                q = f"{prefix}.{sub.name}" if prefix else sub.name
                yield from walk(sub, q, True)
            else:
                yield from walk(sub, prefix, in_class)
    yield from walk(tree, "", False)


def analyze(root: str, make_finding) -> List:
    findings = []
    for rel, ap in iter_py_files(root):
        tree = parse_file(ap)
        if tree is None:
            continue
        mod = module_name(rel)
        for qual, fn, is_method in _scopes(tree):
            remote_defs = _remote_defs_in(fn)
            if not remote_defs:
                continue
            scope = _EnclosingScope(fn, is_method)
            for rdef in remote_defs:
                findings.extend(_check_remote_def(
                    mod, qual, rdef, scope, rel, make_finding))
    return findings


def _check_remote_def(mod: str, encl_qual: str, rdef: ast.FunctionDef,
                      scope: _EnclosingScope, rel: str,
                      make_finding) -> List:
    out = []
    free = _free_vars(rdef)
    subject = f"{mod}.{encl_qual}.{rdef.name}"
    if scope.is_method and "self" in free:
        out.append(make_finding(
            f"{PASS}:self-capture:{subject}",
            f"remote task {subject} captures 'self' from the enclosing "
            f"method — the whole instance is serialized into every "
            f"submission", rel, free["self"]))
    # defaults cross serialization exactly like closure cells do
    for default in rdef.args.defaults + [
            d for d in rdef.args.kw_defaults if d is not None]:
        for n in ast.walk(default):
            if isinstance(n, ast.Name):
                free.setdefault(n.id, n.lineno)
    for name, line in sorted(free.items()):
        kind = scope.kinds.get(name)
        if kind is None:
            continue
        noun = {"resource": "a process-local resource (lock/file/"
                            "socket/thread)",
                "array": "an array built in the enclosing scope",
                "module": "a function-locally imported module"}[kind]
        out.append(make_finding(
            f"{PASS}:{kind}-capture:{subject}:{name}",
            f"remote task {subject} captures '{name}' — {noun} — "
            f"which is serialized by value on every submission",
            rel, line))
    return out
