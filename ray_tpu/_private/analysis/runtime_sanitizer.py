"""raysan's dynamic half — a runtime sanitizer mirroring the static passes.

Armed by ``RAY_TPU_SANITIZE=1`` (read once at import, like
``runtime_checks``), or programmatically via :func:`arm` in tests.
Everything here is zero-cost when disarmed: ``wrap_lock`` returns the
raw lock unchanged, the ledger/track entry points are a single module-
global branch, and no state accumulates.

Three recorders, each the dynamic witness of a static pass:

- **lock witness** (mirrors ``lock_order``): every lock wrapped with
  :func:`wrap_lock` records, per thread, which locks were already held
  at each acquire. At shutdown the observed edge set is diffed against
  the static acquisition graph — an observed edge whose REVERSE exists
  (statically or dynamically) is an order inversion, i.e. a deadlock
  the chaos soak merely got lucky on. Edges the static pass never saw
  are reported separately as *uncharted* (a resolution blind spot, not
  a bug).
- **leak ledger** (mirrors ``ref_lifecycle``): every shm-arena /
  spill-tier allocation is recorded with its owning-task attribution
  (best effort, from the worker's current task context — the same id
  the task-event plane keys on) and removed on free. At
  ``ray_tpu.shutdown()`` a ledger entry whose ObjectID has no row left
  in the ReferenceCounter is a leak: the object went out of scope but
  its bytes were never freed. A parallel live-instance census of
  registered ``ObjectRef``\\ s catches the inverse bug — a refcount row
  held up by a decref that never happened (local > 0 with zero live
  handles).
- **wire schema** (mirrors ``wire_protocol``): the static channel
  table is compiled into tag -> arity-set schemas at arm time; each
  recv dispatcher feeds live messages through :func:`check_wire`, so a
  send site the static table does not model shows up as a violation
  instead of silently drifting.

Violations are RECORDED, never raised: a sanitizer that kills a daemon
thread mid-soak hides every later violation. ``last_report()`` exposes
the assembled shutdown report to tests.
"""

from __future__ import annotations

import logging
import os
import threading
import weakref
from typing import Dict, List, Optional, Set, Tuple

logger = logging.getLogger(__name__)

_ENABLED = os.environ.get("RAY_TPU_SANITIZE", "") == "1"

#: synthetic tags injected into recv queues locally (never wire traffic)
_SYNTHETIC_TAGS = {"__died__"}


def enabled() -> bool:
    return _ENABLED


def arm() -> None:
    """Enable the sanitizer and reset all recorded state (tests)."""
    global _ENABLED
    _ENABLED = True
    reset()


def disarm() -> None:
    global _ENABLED
    _ENABLED = False
    reset()


def reset() -> None:
    global _observed_edges, _ledger, _external, _live_refs
    global _wire_violations, _wire_schema, _owner_provider, _last_report
    _observed_edges = {}
    _ledger = {}
    _external = set()
    _live_refs = {}
    _wire_violations = []
    _wire_schema = None
    _owner_provider = None
    _last_report = None


# ---------------------------------------------------------------------------
# lock witness
# ---------------------------------------------------------------------------

#: (outer_id, inner_id) -> name of the first thread that interleaved them
_observed_edges: Dict[Tuple[str, str], str] = {}
_tls = threading.local()


def _held_stack() -> List[str]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class _WitnessLock:
    """Transparent lock proxy recording acquisition order per thread.

    All bookkeeping is plain dict/list mutation under the GIL — the
    witness must never take a lock of its own while a real acquire is
    in flight, or it would add edges to the very graph it audits.
    """

    __slots__ = ("_lock", "_id")

    def __init__(self, lock, lock_id: str):
        self._lock = lock
        self._id = lock_id

    def acquire(self, *args, **kwargs):
        got = self._lock.acquire(*args, **kwargs)
        if got:
            me = self._id
            thread = None
            for outer in _held_stack():
                if outer != me and (outer, me) not in _observed_edges:
                    if thread is None:
                        thread = threading.current_thread().name
                    _observed_edges[(outer, me)] = thread
            _held_stack().append(me)
        return got

    def release(self):
        st = _held_stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == self._id:
                del st[i]
                break
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        # locked(), _is_owned(), _recursion_count()... — forwarded so
        # assert_holds and Condition plumbing behave as on the raw lock
        return getattr(self._lock, name)


def wrap_lock(lock, lock_id: str):
    """Witness-wrap ``lock`` under RAY_TPU_SANITIZE=1; identity when off.

    ``lock_id`` must match the static pass's naming — the lock
    definition's ``module.Class.attr`` relative to the package root —
    or the shutdown diff compares disjoint universes.
    """
    if not _ENABLED:
        return lock
    return _WitnessLock(lock, lock_id)


def observed_edges() -> Set[Tuple[str, str]]:
    return set(_observed_edges)


def lock_witness_violations(
        static_edges: Optional[Set[Tuple[str, str]]] = None
) -> Tuple[List[str], List[str]]:
    """(inversions, uncharted) of the observed order vs the static graph.

    An inversion is an observed edge (A held while B acquired) whose
    reverse edge exists — in the static graph or in this run's own
    observations. Uncharted edges (observed, absent from the static
    graph in either direction) are returned for visibility but are not
    violations: static resolution under-approximates by design.
    """
    if static_edges is None:
        from ray_tpu._private.analysis import PACKAGE_ROOT, lock_order
        static_edges = lock_order.collect_edges(PACKAGE_ROOT)
    observed = set(_observed_edges)
    inversions = []
    for a, b in sorted(observed):
        if (b, a) in static_edges:
            inversions.append(
                f"runtime order {a} -> {b} (thread "
                f"{_observed_edges[(a, b)]}) inverts static edge "
                f"{b} -> {a}")
        elif (b, a) in observed and a < b:
            inversions.append(
                f"runtime orders {a} -> {b} and {b} -> {a} both "
                f"observed (threads {_observed_edges[(a, b)]} / "
                f"{_observed_edges[(b, a)]})")
    uncharted = [f"{a} -> {b}" for a, b in sorted(observed)
                 if (a, b) not in static_edges
                 and (b, a) not in static_edges]
    return inversions, uncharted


# ---------------------------------------------------------------------------
# shm / ObjectRef leak ledger
# ---------------------------------------------------------------------------

#: oid hex -> {"kind", "nbytes", "owner"}
_ledger: Dict[str, Dict] = {}
#: oid hexes referenced outside this process (ray:// client pins)
_external: Set[str] = set()
#: oid hex -> number of live registered ObjectRef instances
_live_refs: Dict[str, int] = {}
_owner_provider = None


def set_owner_provider(fn) -> None:
    """Install a zero-arg callable resolving the current task context
    (the task-event plane's id) for allocation attribution."""
    global _owner_provider
    _owner_provider = fn


def ledger_alloc(kind: str, object_id, nbytes: int) -> None:
    if not _ENABLED:
        return
    owner = "?"
    if _owner_provider is not None:
        try:
            owner = _owner_provider()
        except Exception:
            pass
    # an arena object migrating to the spill tier is still the same
    # logical allocation — keep the original record
    _ledger.setdefault(object_id.hex(), {
        "kind": kind, "nbytes": int(nbytes), "owner": owner})


def ledger_free(object_id) -> None:
    if not _ENABLED:
        return
    _ledger.pop(object_id.hex(), None)


def ledger_size() -> int:
    return len(_ledger)


def note_external_ref(object_id) -> None:
    """A reference held outside this process (client pin) keeps the
    object legitimately alive with no local ObjectRef instance."""
    if _ENABLED:
        _external.add(object_id.hex())


def drop_external_ref(object_id) -> None:
    if _ENABLED:
        _external.discard(object_id.hex())


def track_ref(ref) -> None:
    """Census a REGISTERED ObjectRef instance (weak — never extends the
    ref's lifetime)."""
    if not _ENABLED:
        return
    h = ref.object_id().hex()
    _live_refs[h] = _live_refs.get(h, 0) + 1
    try:
        weakref.finalize(ref, _ref_died, h)
    except TypeError:
        # not weakref-able: the count can never decrement, so the
        # census over-estimates liveness — never a false leak report
        pass


def _ref_died(h: str) -> None:
    n = _live_refs.get(h, 0) - 1
    if n > 0:
        _live_refs[h] = n
    else:
        _live_refs.pop(h, None)


def shm_leaks(live_oid_hexes: Set[str]) -> List[str]:
    """Ledger entries whose object no longer has a refcount row: the
    object left scope but its segment was never freed."""
    out = []
    for h, entry in sorted(_ledger.items()):
        if h in live_oid_hexes or h in _external:
            continue
        out.append(f"{entry['kind']} segment {h[:16]}… "
                   f"({entry['nbytes']} bytes, owner {entry['owner']}) "
                   f"out of scope but never freed")
    return out


def ref_leaks(counter_snapshot: Dict) -> List[str]:
    """Refcount rows with a positive local count but zero live
    registered ObjectRef instances: a decref was lost, the row (and
    everything it pins) can never be reclaimed."""
    out = []
    for oid, (local, submitted, borrowers, pinned) in sorted(
            counter_snapshot.items(), key=lambda kv: kv[0].hex()):
        h = oid.hex()
        if local > 0 and _live_refs.get(h, 0) == 0 \
                and h not in _external and not pinned:
            out.append(f"object {h[:16]}… local={local} with no live "
                       f"ObjectRef instance (lost decref)")
    return out


# ---------------------------------------------------------------------------
# wire-message schema assertions
# ---------------------------------------------------------------------------

_wire_violations: List[str] = []
_wire_schema = None  # channel -> (tag -> arity set, tag-only allow set)
_MAX_WIRE_VIOLATIONS = 100


def _build_wire_schema():
    """Compile the static channel table into live-checkable schemas —
    generated, not hand-maintained, so the two can't drift."""
    import os as _os

    from ray_tpu._private.analysis import PACKAGE_ROOT, wire_protocol
    from ray_tpu._private.analysis._astutil import parse_file

    schema = {}
    for ch in wire_protocol.DEFAULT_CHANNELS:
        sent: Dict[str, Set[int]] = {}
        for relpath in {s.file for s in ch.sends}:
            tree = parse_file(_os.path.normpath(
                _os.path.join(PACKAGE_ROOT, relpath)))
            if tree is None:
                continue
            specs = [s for s in ch.sends if s.file == relpath]
            for tag, arities in wire_protocol.collect_sends(
                    tree, specs).items():
                sent.setdefault(tag, set()).update(arities)
        allow = set(ch.assume_sent) | set(ch.assume_handled) \
            | _SYNTHETIC_TAGS
        schema[ch.name] = (sent, allow)
    return schema


def check_wire(channel: str, msg) -> None:
    """Validate one received message against the channel's generated
    schema; violations are recorded, never raised."""
    if not _ENABLED:
        return
    global _wire_schema
    if _wire_schema is None:
        _wire_schema = _build_wire_schema()
    sent, allow = _wire_schema.get(channel, ({}, set()))
    if not isinstance(msg, tuple) or not msg \
            or not isinstance(msg[0], str):
        _record_wire(f"[{channel}] non-tagged frame {type(msg).__name__}")
        return
    tag = msg[0]
    if tag in allow:
        return
    arities = sent.get(tag)
    if arities is None:
        _record_wire(f"[{channel}] tag {tag!r} not in the static "
                     f"channel table")
    elif len(msg) not in arities:
        _record_wire(f"[{channel}] tag {tag!r} arrived with arity "
                     f"{len(msg)}, static senders produce "
                     f"{sorted(arities)}")
    if tag == "many" and len(msg) > 1 and isinstance(msg[1],
                                                     (list, tuple)):
        for sub in msg[1]:
            check_wire(channel, sub)


def _record_wire(violation: str) -> None:
    if len(_wire_violations) < _MAX_WIRE_VIOLATIONS \
            and violation not in _wire_violations:
        _wire_violations.append(violation)


def wire_violations() -> List[str]:
    return list(_wire_violations)


# ---------------------------------------------------------------------------
# shutdown report
# ---------------------------------------------------------------------------

_last_report: Optional[Dict] = None


def report_at_shutdown(counter_snapshot: Dict,
                       static_edges: Optional[Set[Tuple[str, str]]] = None
                       ) -> Dict:
    """Assemble the full sanitizer report (called from
    ``Worker.shutdown``); each violation is logged as a warning and the
    report is kept for ``last_report()``."""
    global _last_report
    inversions, uncharted = lock_witness_violations(static_edges)
    report = {
        "lock_inversions": inversions,
        "lock_uncharted": uncharted,
        "shm_leaks": shm_leaks({oid.hex() for oid in counter_snapshot}),
        "ref_leaks": ref_leaks(counter_snapshot),
        "wire_violations": wire_violations(),
    }
    for section in ("lock_inversions", "shm_leaks", "ref_leaks",
                    "wire_violations"):
        for v in report[section]:
            logger.warning("sanitizer [%s] %s", section, v)
    _last_report = report
    return report


def last_report() -> Optional[Dict]:
    return _last_report


def clean(report: Optional[Dict] = None) -> bool:
    """True when the report carries no violations (uncharted edges are
    informational and do not count)."""
    r = _last_report if report is None else report
    if r is None:
        return True
    return not (r["lock_inversions"] or r["shm_leaks"]
                or r["ref_leaks"] or r["wire_violations"])


reset()
