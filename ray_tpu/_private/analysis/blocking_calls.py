"""Pass 8 — blocking calls in no-block contexts.

Two kinds of code in this repo must never block indefinitely:

- **event-loop zones** — the single-threaded receive/dispatch loops
  that everything else is waiting BEHIND (``_ZONES`` below: the node
  daemon's command loop, both pool demux loops, the scheduler tick
  thread). A blocking get/result/acquire there wedges the whole plane,
  not one task.
- **actor methods** — methods of ``@remote`` classes. A blocking
  ``ray_tpu.get`` inside an actor is the textbook distributed
  deadlock: the actor waits on a task that needs the actor's own slot
  (or its caller's) to run. Ray's own docs forbid it; async actors
  ``await`` instead.

Flagged shapes:

- **blocking-get**: ``ray_tpu.get(...)`` / ``worker.get(...)`` /
  ``self._worker.get(...)`` with no ``timeout=`` argument.
- **blocking-result**: ``fut.result()`` with no timeout.
- **bare-acquire**: ``<lock-ish>.acquire()`` with neither
  ``timeout=`` nor ``blocking=False`` — invisible to the with-based
  lock-order pass and undiagnosable when it deadlocks.

``allow`` suppresses reviewed sites by finding key (deliberate
blocking with an out-of-band watchdog). ``with lock:`` statements are
NOT flagged — they are the lock_order pass's territory and most
zone bodies legitimately take their own short-hold locks.
"""

from __future__ import annotations

import ast
from typing import FrozenSet, List, Optional, Tuple

from ray_tpu._private.analysis._astutil import (find_function,
                                                iter_py_files,
                                                module_name, parse_file)

PASS = "blocking_calls"

#: (module, "Class.method") bodies that run on an event/demux loop
_ZONES: Tuple[Tuple[str, str], ...] = (
    ("_private.runtime.node_daemon", "NodeDaemon.run"),
    ("_private.runtime.remote_pool", "RemoteNodePool._demux_loop"),
    ("_private.runtime.process_pool", "ProcessWorkerPool._demux_loop"),
    ("_private.scheduler.tensor", "TensorScheduler._tick_loop"),
)

#: reviewed sites where blocking is deliberate (watchdogged elsewhere)
DEFAULT_ALLOW: FrozenSet[str] = frozenset()


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _base_chain(node: ast.AST) -> str:
    """'self._worker.get' -> 'self._worker' tail name for matching."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _blocking_get(call: ast.Call) -> bool:
    """A worker/driver get with no timeout: positional timeout counts
    as a timeout only when it is not the literal None."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "get"):
        return False
    if _base_chain(f.value) not in ("ray_tpu", "ray", "worker", "_worker"):
        return False
    to = _kw(call, "timeout")
    if to is None and len(call.args) >= 2:
        to = call.args[1]
    if to is None:
        return True
    return isinstance(to, ast.Constant) and to.value is None


def _blocking_result(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "result"
            and not call.args and _kw(call, "timeout") is None)


def _bare_acquire(call: ast.Call) -> Optional[str]:
    """Lock-ish name if the call is an unbounded ``.acquire()``."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "acquire"):
        return None
    base = _base_chain(f.value).lower()
    if not ("lock" in base or "cv" in base or "cond" in base
            or "sem" in base):
        return None
    if _kw(call, "timeout") is not None:
        return None
    b = _kw(call, "blocking")
    if b is not None and isinstance(b, ast.Constant) and b.value is False:
        return None
    if call.args:  # positional blocking=False
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and a0.value is False:
            return None
    return _base_chain(f.value)


def _scan_body(fn: ast.FunctionDef, subject: str, rel: str,
               make_finding, allow: FrozenSet[str]) -> List:
    out = []
    seen = set()
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Call):
            continue
        if _blocking_get(sub):
            key = f"{PASS}:blocking-get:{subject}"
        elif _blocking_result(sub):
            key = f"{PASS}:blocking-result:{subject}"
        else:
            lock = _bare_acquire(sub)
            if lock is None:
                continue
            key = f"{PASS}:bare-acquire:{subject}:{lock}"
        if key in allow or key in seen:
            continue
        seen.add(key)
        shape = key.split(":")[1]
        out.append(make_finding(
            key,
            f"{subject} makes a {shape.replace('-', ' ')} call with no "
            f"timeout in a no-block context (event-loop zone or actor "
            f"method)", rel, sub.lineno))
    return out


def _remote_classes(tree: ast.Module) -> List[ast.ClassDef]:
    out = []
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for dec in node.decorator_list:
            d = dec.func if isinstance(dec, ast.Call) else dec
            name = d.attr if isinstance(d, ast.Attribute) else (
                d.id if isinstance(d, ast.Name) else None)
            if name == "remote":
                out.append(node)
                break
    return out


def analyze(root: str, make_finding,
            allow: FrozenSet[str] = DEFAULT_ALLOW) -> List:
    findings = []
    zones = {mod: [] for mod, _ in _ZONES}
    for mod, qual in _ZONES:
        zones[mod].append(qual)
    for rel, ap in iter_py_files(root):
        tree = parse_file(ap)
        if tree is None:
            continue
        mod = module_name(rel)
        # event-loop zones
        for qual in zones.get(mod, ()):
            for fn in find_function(tree, qual):
                findings.extend(_scan_body(
                    fn, f"{mod}.{qual}", rel, make_finding, allow))
        # actor methods
        for cls in _remote_classes(tree):
            for stmt in cls.body:
                if not isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                findings.extend(_scan_body(
                    stmt, f"{mod}.{cls.name}.{stmt.name}",
                    rel, make_finding, allow))
    return findings
