"""Pass 3 — wire-protocol conformance.

ray_tpu's control links are framed pickled tuples whose first element
is a string tag. Nothing ties a send site's shape to its recv-dispatch
branch except convention, so drift (a renamed tag, a new field, a
removed branch) fails silently at runtime as an ignored message or an
IndexError on a daemon thread. This pass makes the convention checkable:

- **send sites**: every literal ``("tag", …)`` tuple passed to a
  channel's send wrapper is collected as (tag, arity); wrapper deltas
  (``_log_request`` prepends a request id) and fixed-shape wrappers
  (``_remote_round(kind, payload)`` → 2-tuple) are modeled per channel.
- **recv dispatch**: in each dispatcher function we find the message
  variable (assigned from ``*.recv()`` / the wrapper's parameter), the
  tag variable (``kind = msg[0]``), then every ``== "tag"`` /
  ``in ("a", "b")`` branch, recording the deepest constant index into
  the message used in that branch and any exact tuple-unpacks.

Violations: a tag **sent but unhandled**, **handled but never sent**
(dead branch — or a sender that was deleted without its branch), and
**arity drift** (a branch indexing past every sent arity for its tag,
or an exact unpack length no sender produces).

Channels whose payloads are relayed opaquely (``to_w``/``to_ctrl``) or
produced dynamically (protocol error frames) are declared in
``assume_sent``/``assume_handled`` rather than silently skipped. The
byte-oriented peer-pull subprotocol (get/meta/ok/miss chunk streams) is
out of scope — it has its own length-prefixed framing and tests.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu._private.analysis._astutil import (find_function,
                                                parse_file)

PASS = "wire"


@dataclass
class SendSpec:
    file: str            # relpath under the scan root
    callee: str          # terminal name of the send wrapper
    #: "tuple_arg": first positional arg is a literal ("tag", ...) tuple;
    #: arity = len(tuple) + delta.
    #: "first_arg_tag": first positional arg is the tag string itself;
    #: the wrapper sends a tuple of fixed_arity elements.
    style: str = "tuple_arg"
    delta: int = 0
    fixed_arity: int = 2


@dataclass
class RecvSpec:
    file: str
    func: str            # "Class.method" or "func"


@dataclass
class ChannelSpec:
    name: str
    sends: Sequence[SendSpec]
    recvs: Sequence[RecvSpec]
    assume_sent: Set[str] = field(default_factory=set)
    assume_handled: Set[str] = field(default_factory=set)


#: the repo's real channel table (file paths relative to ray_tpu/)
DEFAULT_CHANNELS: List[ChannelSpec] = [
    ChannelSpec(
        name="head_to_daemon",
        sends=[
            SendSpec("_private/runtime/remote_pool.py", "_send_daemon"),
            SendSpec("_private/runtime/remote_pool.py", "_log_request",
                     delta=1),
            # node-death control frames originate head-side: the fence
            # on a rejoin-after-declared-dead readopt and the route
            # invalidation broadcast to every surviving daemon
            SendSpec("_private/worker.py", "_send_daemon"),
        ],
        recvs=[RecvSpec("_private/runtime/node_daemon.py",
                        "NodeDaemon.run")],
        # to_w/to_ctrl are built dynamically by _ProxyConn.send; error
        # frames come from protocol.mismatch_error at handshake time
        assume_sent={"to_w", "to_ctrl", "error"},
    ),
    ChannelSpec(
        name="daemon_to_head",
        # _send_head buffers report-class tags through the outbox and
        # wraps them in ("seq", n, depth, is_replay, inner) envelopes;
        # _send_head_raw is the direct socket write (the envelope
        # itself, replays, and the clock handshake go through it)
        sends=[SendSpec("_private/runtime/node_daemon.py",
                        "_send_head"),
               SendSpec("_private/runtime/node_daemon.py",
                        "_send_head_raw")],
        recvs=[RecvSpec("_private/runtime/remote_pool.py",
                        "RemoteNodePool._demux_loop"),
               RecvSpec("_private/runtime/remote_pool.py",
                        "RemoteNodePool._dispatch_daemon_msg")],
    ),
    ChannelSpec(
        name="owner_to_worker",
        sends=[
            SendSpec("_private/runtime/process_pool.py", "send"),
            # control-ring writer: ("env", envelope) rides a shm slot
            # when it fits, the pipe verbatim otherwise — one schema
            # covers both transports
            SendSpec("_private/runtime/process_pool.py", "_ring_send"),
            SendSpec("actor.py", "_remote_round",
                     style="first_arg_tag", fixed_arity=2),
        ],
        recvs=[
            RecvSpec("_private/runtime/worker_process.py",
                     "_WorkerRunner.run"),
            RecvSpec("_private/runtime/worker_process.py",
                     "_WorkerRunner.rpc"),
            RecvSpec("_private/runtime/worker_process.py",
                     "_WorkerRunner._ctrl_loop"),
            RecvSpec("_private/runtime/worker_process.py",
                     "_WorkerRunner._run_nested"),
            # the node daemon decodes a bookkeeping copy of every lease
            # frame it relays head->worker — including the remote lease
            # envelope ("env", blob), which extends the PR-11 batched
            # path to remote pools — so its dispatcher is a second recv
            # of this channel and drifts are caught on both decoders
            RecvSpec("_private/runtime/node_daemon.py",
                     "NodeDaemon._register_lease_msg"),
        ],
        # "reply" is also DISPATCHED by the worker's rpc() wait loop —
        # arity there is checked like any branch; node_daemon relays
        # head payloads through _to_worker opaquely (dynamic msg)
        # ("p2p", local, p2p) two-level adverts are injected by the
        # daemon's _intercept/_apply_resview through _to_worker
        # (dynamic msg var, not a literal send site)
        assume_sent={"p2p"},
    ),
    ChannelSpec(
        name="worker_to_owner",
        sends=[
            SendSpec("_private/runtime/worker_process.py", "send"),
            SendSpec("_private/runtime/worker_process.py", "_emit"),
            # completion-ring writer: ("cenv", envelope) on the shm
            # ring (the pipe fallback re-sends the buffered originals,
            # already covered by the send/_emit specs above)
            SendSpec("_private/runtime/worker_process.py", "_ring_emit"),
        ],
        recvs=[
            RecvSpec("_private/runtime/process_pool.py",
                     "ProcessWorkerPool._demux_loop"),
            RecvSpec("_private/runtime/process_pool.py",
                     "ProcessWorkerPool._handle_worker_msg"),
            RecvSpec("_private/runtime/process_pool.py",
                     "ProcessWorkerPool._handle_ring_msg"),
        ],
        # the daemon's _intercept peeks at done/err tails in transit
        # but the authoritative dispatcher is the owner pool
    ),
    ChannelSpec(
        name="peer_actor_lane",
        # daemon<->daemon actor-call lane riding the peer object plane:
        # _lane_send is the single framed-send point for the caller
        # side (("acall", envelope)), the executing side (("ares", tid,
        # status, data, timing)), and the resource-view gossip frames
        # (("rview", view) — tentpole d: daemons re-share the head's
        # freshest view so local admission survives a slow/rejoining
        # head; _peer_serve adopts on epoch match + strictly newer v)
        sends=[SendSpec("_private/runtime/node_daemon.py",
                        "_lane_send")],
        recvs=[RecvSpec("_private/runtime/node_daemon.py",
                        "NodeDaemon._peer_serve"),
               RecvSpec("_private/runtime/node_daemon.py",
                        "NodeDaemon._lane_reader")],
        # "get" belongs to the byte-oriented peer-pull subprotocol
        # (chunked conn.send frames, out of scope per module docstring);
        # "ares" is validated inline by _lane_reader's guard clause and
        # unpacked in _on_ares, which the branch collector cannot see
        assume_sent={"get"},
        assume_handled={"ares"},
    ),
]


@dataclass
class FrameVarSpec:
    """One function that builds or reads a dict-shaped frame through a
    variable: ``var = {...literal...}`` / ``var["k"] = v`` on the
    producer side, ``var.get("k")`` / ``var["k"]`` on the consumer
    side. ``var`` matches a local name (``view``) or an attribute's
    terminal name (``_resview`` matches ``self._resview``)."""
    file: str
    func: str            # "Class.method" or "func"
    var: str             # local name or attribute terminal name


@dataclass
class FrameFieldSpec:
    """Dict-shaped frame riding an already-checked channel. The
    tag+arity pass above sees ``("rview", view)`` as a healthy 2-tuple
    no matter what keys ``view`` carries, so field drift — a consumer
    reading a key no producer writes (silently None forever), or a
    producer shipping a key nothing reads (dead payload) — needs its
    own producer/consumer table."""
    name: str
    producers: Sequence[FrameVarSpec]
    consumers: Sequence[FrameVarSpec]
    #: keys consumers may read that no modeled producer writes
    #: (injected in transit by relays the table does not model)
    assume_produced: Set[str] = field(default_factory=set)
    #: keys producers write that no modeled consumer reads
    assume_read: Set[str] = field(default_factory=set)


#: dict-shaped frame field tables (file paths relative to ray_tpu/)
DEFAULT_FRAME_FIELDS: List[FrameFieldSpec] = [
    FrameFieldSpec(
        # the head's resource-view push (head_to_daemon "resview"
        # frames) and the daemons' peer gossip re-share of the same
        # dict (peer_actor_lane "rview" frames). The "wm" row is the
        # QoS top-spilled-tier watermark: written only when the plane
        # is on (qos=False frames stay byte-for-byte pre-QoS), read by
        # local admission so a low-tier nested task cannot locally
        # dispatch past a spilled high-tier one.
        name="resview",
        producers=[
            FrameVarSpec("_private/worker.py",
                         "Worker._resview_push_loop", "view"),
            # gossip re-shares the head's dict verbatim plus an origin
            # stamp for ghost-view eviction
            FrameVarSpec("_private/runtime/node_daemon.py",
                         "NodeDaemon._gossip_loop", "view"),
        ],
        consumers=[
            FrameVarSpec("_private/runtime/node_daemon.py",
                         "NodeDaemon._apply_resview", "view"),
            FrameVarSpec("_private/runtime/node_daemon.py",
                         "NodeDaemon._maybe_local_submit", "view"),
            FrameVarSpec("_private/runtime/node_daemon.py",
                         "NodeDaemon._gossip_loop", "view"),
            # p2p caller side reads the node index off the stored view
            # through the attribute receiver (self._resview)
            FrameVarSpec("_private/runtime/node_daemon.py",
                         "NodeDaemon._maybe_p2p_call", "_resview"),
        ],
    ),
]


@dataclass
class OpChannelSpec:
    """ray:// op-mode channel: ``_rpc("op", *payload)`` client calls
    against ``_op_<name>(self, session, *payload)`` server methods."""
    name: str
    client_file: str
    rpc_callees: Sequence[str]
    server_file: str
    server_class: str
    op_prefix: str = "_op_"
    assume_sent: Set[str] = field(default_factory=set)


DEFAULT_OP_CHANNELS: List[OpChannelSpec] = [
    OpChannelSpec(
        name="ray_client",
        client_file="_private/client.py",
        rpc_callees=("_rpc", "_send_oneway"),
        server_file="_private/client.py",
        server_class="ClientServer",
    ),
]


# ---------------------------------------------------------------------------
# send-site extraction
# ---------------------------------------------------------------------------

def _callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def collect_sends(tree: ast.Module,
                  specs: Sequence[SendSpec]) -> Dict[str, Set[int]]:
    """tag -> set of sent arities, over one file's send specs."""
    by_callee = {s.callee: s for s in specs}
    out: Dict[str, Set[int]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        spec = by_callee.get(_callee_name(node))
        if spec is None:
            continue
        first = node.args[0]
        if spec.style == "first_arg_tag":
            if (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                out.setdefault(first.value, set()).add(spec.fixed_arity)
            continue
        if (isinstance(first, ast.Tuple) and first.elts
                and isinstance(first.elts[0], ast.Constant)
                and isinstance(first.elts[0].value, str)):
            out.setdefault(first.elts[0].value, set()).add(
                len(first.elts) + spec.delta)
    return out


# ---------------------------------------------------------------------------
# recv-dispatch extraction
# ---------------------------------------------------------------------------

@dataclass
class Handled:
    max_index: int = 0
    unpack_lens: Set[int] = field(default_factory=set)
    line: int = 0


def _recv_msg_vars(fn: ast.FunctionDef) -> Set[str]:
    """Names bound from a ``*.recv*()`` call, plus a ``msg`` parameter
    (wrapper dispatchers like _handle_worker_msg take the tuple as an
    argument)."""
    out: Set[str] = set()
    for arg in fn.args.args:
        if arg.arg in ("msg", "wmsg"):
            out.add(arg.arg)
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            name = _callee_name(node.value)
            if name and "recv" in name:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        # msg = self._inbox.pop(0) — the worker run-loop's second source
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _callee_name(node.value) == "pop"):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "msg":
                    out.add(tgt.id)
    return out


def _kind_vars(fn: ast.FunctionDef, msg_vars: Set[str]) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Subscript)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id in msg_vars
                and isinstance(node.value.slice, ast.Constant)
                and node.value.slice.value == 0):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _branch_tags(test: ast.AST, msg_vars: Set[str],
                 kind_vars: Set[str]) -> List[str]:
    """Tags selected by an if-test, [] when the test is not a tag
    dispatch."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
        tags: List[str] = []
        for v in test.values:
            tags.extend(_branch_tags(v, msg_vars, kind_vars))
        return tags
    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return []
    left = test.left
    is_kind = (isinstance(left, ast.Name) and left.id in kind_vars) or (
        isinstance(left, ast.Subscript)
        and isinstance(left.value, ast.Name)
        and left.value.id in msg_vars
        and isinstance(left.slice, ast.Constant)
        and left.slice.value == 0)
    if not is_kind:
        return []
    op = test.ops[0]
    comp = test.comparators[0]
    if isinstance(op, ast.Eq):
        if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
            return [comp.value]
    elif isinstance(op, ast.In) and isinstance(comp, (ast.Tuple,
                                                      ast.List,
                                                      ast.Set)):
        return [e.value for e in comp.elts
                if isinstance(e, ast.Constant)
                and isinstance(e.value, str)]
    return []


def _max_msg_index(body: Sequence[ast.stmt],
                   msg_vars: Set[str]) -> Tuple[int, Set[int]]:
    """Deepest constant integer subscript into a message var inside a
    branch body, plus any exact tuple-unpack lengths."""
    max_idx = 0
    unpacks: Set[int] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in msg_vars
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, int)):
                max_idx = max(max_idx, node.slice.value)
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in msg_vars):
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Tuple, ast.List)) and not any(
                            isinstance(e, ast.Starred) for e in tgt.elts):
                        unpacks.add(len(tgt.elts))
    return max_idx, unpacks


def collect_handlers(tree: ast.Module,
                     spec: RecvSpec) -> Dict[str, Handled]:
    out: Dict[str, Handled] = {}
    for fn in find_function(tree, spec.func):
        msg_vars = _recv_msg_vars(fn)
        if not msg_vars:
            continue
        kind_vars = _kind_vars(fn, msg_vars)
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            tags = _branch_tags(node.test, msg_vars, kind_vars)
            if not tags:
                continue
            max_idx, unpacks = _max_msg_index(node.body, msg_vars)
            for tag in tags:
                h = out.setdefault(tag, Handled(line=node.lineno))
                h.max_index = max(h.max_index, max_idx)
                h.unpack_lens |= unpacks
    return out


# ---------------------------------------------------------------------------
# channel checking
# ---------------------------------------------------------------------------

def check_channel(channel: ChannelSpec, root: str,
                  make_finding) -> List:
    import os
    findings = []
    sent: Dict[str, Set[int]] = {}
    for spec in {s.file for s in channel.sends}:
        tree = parse_file(os.path.normpath(os.path.join(root, spec)))
        if tree is None:
            continue
        file_specs = [s for s in channel.sends if s.file == spec]
        for tag, arities in collect_sends(tree, file_specs).items():
            sent.setdefault(tag, set()).update(arities)
    handled: Dict[str, Handled] = {}
    for spec in channel.recvs:
        tree = parse_file(os.path.normpath(os.path.join(root, spec.file)))
        if tree is None:
            continue
        for tag, h in collect_handlers(tree, spec).items():
            cur = handled.setdefault(tag, Handled(line=h.line))
            cur.max_index = max(cur.max_index, h.max_index)
            cur.unpack_lens |= h.unpack_lens

    recv_file = channel.recvs[0].file if channel.recvs else ""
    for tag in sorted(set(sent) - set(handled) - channel.assume_handled):
        findings.append(make_finding(
            f"{PASS}:sent-unhandled:{channel.name}:{tag}",
            f"[{channel.name}] tag {tag!r} is sent but no recv-dispatch "
            f"branch handles it", recv_file, 0))
    for tag in sorted(set(handled) - set(sent) - channel.assume_sent):
        findings.append(make_finding(
            f"{PASS}:handled-unsent:{channel.name}:{tag}",
            f"[{channel.name}] dispatch branch for tag {tag!r} exists "
            f"but no send site produces it", recv_file,
            handled[tag].line))
    for tag in sorted(set(sent) & set(handled)):
        arities = sent[tag]
        h = handled[tag]
        if h.max_index >= max(arities):
            findings.append(make_finding(
                f"{PASS}:arity:{channel.name}:{tag}",
                f"[{channel.name}] branch for {tag!r} indexes "
                f"msg[{h.max_index}] but senders send at most "
                f"{max(arities)} elements", recv_file, h.line))
        for ln in sorted(h.unpack_lens):
            if ln not in arities:
                findings.append(make_finding(
                    f"{PASS}:arity:{channel.name}:{tag}:unpack{ln}",
                    f"[{channel.name}] branch for {tag!r} unpacks "
                    f"exactly {ln} elements but senders send "
                    f"{sorted(arities)}", recv_file, h.line))
    return findings


def _frame_var_matches(node: ast.AST, var: str) -> bool:
    return ((isinstance(node, ast.Name) and node.id == var)
            or (isinstance(node, ast.Attribute) and node.attr == var))


def collect_fields_produced(tree: ast.Module,
                            spec: FrameVarSpec) -> Set[str]:
    """String keys the function writes into its frame var: dict-literal
    assignments (``var = {"k": ...}``) and key stores
    (``var["k"] = ...``)."""
    out: Set[str] = set()
    for fn in find_function(tree, spec.func):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if (_frame_var_matches(tgt, spec.var)
                        and isinstance(node.value, ast.Dict)):
                    out.update(k.value for k in node.value.keys
                               if isinstance(k, ast.Constant)
                               and isinstance(k.value, str))
                if (isinstance(tgt, ast.Subscript)
                        and _frame_var_matches(tgt.value, spec.var)
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)):
                    out.add(tgt.slice.value)
    return out


def collect_fields_read(tree: ast.Module,
                        spec: FrameVarSpec) -> Dict[str, int]:
    """key -> first line where the function reads it from the frame
    var, via ``var.get("k")`` or a ``var["k"]`` load."""
    out: Dict[str, int] = {}
    for fn in find_function(tree, spec.func):
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and _frame_var_matches(node.func.value, spec.var)
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                out.setdefault(node.args[0].value, node.lineno)
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and _frame_var_matches(node.value, spec.var)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                out.setdefault(node.slice.value, node.lineno)
    return out


def check_frame_fields(table: FrameFieldSpec, root: str,
                       make_finding) -> List:
    import os
    findings = []
    produced: Set[str] = set()
    for spec in table.producers:
        tree = parse_file(os.path.normpath(os.path.join(root, spec.file)))
        if tree is None:
            continue
        produced |= collect_fields_produced(tree, spec)
    read: Dict[str, int] = {}
    read_file = table.consumers[0].file if table.consumers else ""
    for spec in table.consumers:
        tree = parse_file(os.path.normpath(os.path.join(root, spec.file)))
        if tree is None:
            continue
        for key, line in collect_fields_read(tree, spec).items():
            read.setdefault(key, line)
    if not produced or not read:
        return findings  # a moved/renamed function: nothing to compare
    for key in sorted(set(read) - produced - table.assume_produced):
        findings.append(make_finding(
            f"{PASS}:field-unproduced:{table.name}:{key}",
            f"[{table.name}] consumers read frame field {key!r} but no "
            f"producer writes it (silently None forever)", read_file,
            read[key]))
    for key in sorted(produced - set(read) - table.assume_read):
        findings.append(make_finding(
            f"{PASS}:field-unread:{table.name}:{key}",
            f"[{table.name}] producers ship frame field {key!r} but no "
            f"consumer reads it (dead payload)", read_file, 0))
    return findings


def check_op_channel(channel: OpChannelSpec, root: str,
                     make_finding) -> List:
    import os
    findings = []
    client_tree = parse_file(os.path.normpath(
        os.path.join(root, channel.client_file)))
    server_tree = parse_file(os.path.normpath(
        os.path.join(root, channel.server_file)))
    if client_tree is None or server_tree is None:
        return findings

    #: op -> set of payload-arg counts at call sites
    sent: Dict[str, Set[int]] = {}
    callees = set(channel.rpc_callees)
    for node in ast.walk(client_tree):
        if (isinstance(node, ast.Call) and node.args
                and _callee_name(node) in callees
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            sent.setdefault(node.args[0].value, set()).add(
                len(node.args) - 1)

    #: op -> (required_payload, max_payload or None for *args, line)
    defined: Dict[str, Tuple[int, Optional[int], int]] = {}
    for node in server_tree.body:
        if not (isinstance(node, ast.ClassDef)
                and node.name == channel.server_class):
            continue
        for sub in node.body:
            if (isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and sub.name.startswith(channel.op_prefix)):
                op = sub.name[len(channel.op_prefix):]
                # params minus (self, session)
                n = len(sub.args.args) - 2
                required = n - len(sub.args.defaults)
                maximum = None if sub.args.vararg else n
                defined[op] = (required, maximum, sub.lineno)

    for op in sorted(set(sent) - set(defined)):
        findings.append(make_finding(
            f"{PASS}:op-undefined:{channel.name}:{op}",
            f"[{channel.name}] client sends op {op!r} but the server "
            f"defines no {channel.op_prefix}{op}", channel.server_file,
            0))
    for op in sorted(set(defined) - set(sent) - channel.assume_sent):
        findings.append(make_finding(
            f"{PASS}:op-unsent:{channel.name}:{op}",
            f"[{channel.name}] server defines "
            f"{channel.op_prefix}{op} but the client never sends it",
            channel.server_file, defined[op][2]))
    for op in sorted(set(sent) & set(defined)):
        required, maximum, line = defined[op]
        for n in sorted(sent[op]):
            if n < required or (maximum is not None and n > maximum):
                findings.append(make_finding(
                    f"{PASS}:op-arity:{channel.name}:{op}:{n}",
                    f"[{channel.name}] op {op!r} called with {n} "
                    f"payload args but {channel.op_prefix}{op} takes "
                    f"{required}..{maximum}", channel.server_file,
                    line))
    return findings


def analyze(root: str, make_finding,
            channels: Optional[Sequence[ChannelSpec]] = None,
            op_channels: Optional[Sequence[OpChannelSpec]] = None,
            frame_fields: Optional[Sequence[FrameFieldSpec]] = None
            ) -> List:
    findings = []
    for ch in (DEFAULT_CHANNELS if channels is None else channels):
        findings.extend(check_channel(ch, root, make_finding))
    for och in (DEFAULT_OP_CHANNELS if op_channels is None
                else op_channels):
        findings.extend(check_op_channel(och, root, make_finding))
    for ff in (DEFAULT_FRAME_FIELDS if frame_fields is None
               else frame_fields):
        findings.extend(check_frame_fields(ff, root, make_finding))
    return findings
