"""Dynamic counterpart of the static lock passes.

``assert_holds(lock)`` verifies the calling thread actually holds the
lock guarding the structure it is about to touch. It compiles to a
no-op unless ``RAY_TPU_DEBUG_LOCKS=1`` (read once at import, like
other debug gates), so the hot paths it decorates — the GCS object
directory, the task-event ring, the pull manager — pay nothing in
production while chaos soaks and debug runs exercise the same
invariants raylint checks statically.

Ownership detection: ``RLock`` and ``Condition`` expose ``_is_owned``;
a plain ``Lock`` has no owner concept, so the best available check is
``acquire(blocking=False)`` — if that *succeeds*, nobody held the lock
and the caller has a race. (It cannot distinguish "this thread holds
it" from "another thread holds it"; that is exactly the static pass's
job.)
"""

from __future__ import annotations

import os

_ENABLED = os.environ.get("RAY_TPU_DEBUG_LOCKS", "") == "1"


class LockNotHeldError(AssertionError):
    pass


def enabled() -> bool:
    return _ENABLED


def assert_holds(lock, what: str = "") -> None:
    """Raise LockNotHeldError if ``lock`` is demonstrably not held.

    No-op unless RAY_TPU_DEBUG_LOCKS=1.
    """
    if not _ENABLED:
        return
    owned = getattr(lock, "_is_owned", None)
    if owned is not None:
        if not owned():
            raise LockNotHeldError(
                f"lock required but not held by this thread"
                f"{': ' + what if what else ''}")
        return
    # plain Lock: a successful non-blocking acquire proves NOBODY held it
    if lock.acquire(blocking=False):
        lock.release()
        raise LockNotHeldError(
            f"lock required but not held by anyone"
            f"{': ' + what if what else ''}")
