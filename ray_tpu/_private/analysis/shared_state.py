"""Pass 2 — unguarded-shared-state detection.

For every class that spawns threads AND designates at least one lock,
classify each mutation of a ``self.X`` attribute as *guarded* (lexically
inside ``with self.<lock>:``, or in a method whose name ends in
``_locked`` — the repo's caller-holds-the-lock convention) or
*unguarded*, then flag:

- **mixed-guard**: an attribute mutated both under the lock and outside
  it (the classic "forgot the lock on one path" race), and
- **unguarded read-modify-write**: ``self.x += 1`` / ``self.d[k] += v``
  style AugAssign outside any lock, when the attribute is touched from
  ≥2 distinct methods (single-method counters are usually confined to
  one thread).

``__init__`` / ``__enter__`` style setup runs before threads exist and
is exempt, as are the lock attributes themselves.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.analysis._astutil import (MUTATING_METHODS,
                                                ClassInfo,
                                                collect_classes,
                                                iter_py_files,
                                                module_name, parse_file)

PASS = "shared_state"

#: methods that run before any thread is spawned (or tear everything
#: down after joins) — mutations there are single-threaded by contract
_EXEMPT_METHODS = {"__init__", "__new__", "__enter__", "__post_init__"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _base_self_attr(node: ast.AST) -> Optional[str]:
    """self.X, self.X[k], self.X.y ... -> "X" (outermost self attr)."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        a = _self_attr(node)
        if a is not None:
            return a
        node = node.value
    return None


class _MethodScan(ast.NodeVisitor):
    """Collect per-attribute mutations with their guard state."""

    def __init__(self, cls: ClassInfo, always_guarded: bool):
        self.cls = cls
        self.always_guarded = always_guarded
        self.depth = 0  # with self.<lock>: nesting depth
        #: attr -> [(guarded, line, is_rmw)]
        self.mutations: Dict[str, List[Tuple[bool, int, bool]]] = {}
        #: attrs read or written at all (for the >=2-methods heuristic)
        self.touched: Set[str] = set()

    def _guarded(self) -> bool:
        return self.always_guarded or self.depth > 0

    def _note(self, attr: Optional[str], line: int,
              rmw: bool = False) -> None:
        if attr is None or attr in self.cls.locks:
            return
        self.mutations.setdefault(attr, []).append(
            (self._guarded(), line, rmw))
        self.touched.add(attr)

    def visit_With(self, node: ast.With) -> None:
        holds = False
        for item in node.items:
            a = _self_attr(item.context_expr)
            if a is not None and a in self.cls.locks:
                holds = True
        if holds:
            self.depth += 1
        for item in node.items:
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        if holds:
            self.depth -= 1

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                self._note(_base_self_attr(tgt), node.lineno)
            else:
                self._note(_self_attr(tgt), node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note(_base_self_attr(node.target), node.lineno, rmw=True)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._note(_self_attr(node.target), node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                self._note(_base_self_attr(tgt), node.lineno)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATING_METHODS:
            self._note(_base_self_attr(f.value), node.lineno)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        a = _self_attr(node)
        if a is not None:
            self.touched.add(a)
        self.generic_visit(node)

    # thread targets defined inline run concurrently, but scanning them
    # with the same guard state is wrong only when they capture the
    # with-block's lock scope — conservatively treat nested defs as
    # separate unguarded scopes
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        inner = _MethodScan(self.cls, always_guarded=False)
        for stmt in node.body:
            inner.visit(stmt)
        for attr, muts in inner.mutations.items():
            self.mutations.setdefault(attr, []).extend(muts)
        self.touched |= inner.touched

    visit_AsyncFunctionDef = visit_FunctionDef


def analyze(root: str, make_finding) -> List:
    findings = []
    for rel, ap in iter_py_files(root):
        tree = parse_file(ap)
        if tree is None:
            continue
        mod = module_name(rel)
        for cls in collect_classes(tree, mod):
            if not cls.spawns_threads or not cls.locks:
                continue
            findings.extend(_check_class(cls, rel, make_finding))
    return findings


def _check_class(cls: ClassInfo, rel: str, make_finding) -> List:
    #: attr -> [(guarded, line, rmw)] across non-exempt methods
    all_muts: Dict[str, List[Tuple[bool, int, bool]]] = {}
    #: attr -> set of method names touching it
    methods_touching: Dict[str, Set[str]] = {}
    for meth in cls.methods():
        if meth.name in _EXEMPT_METHODS:
            continue
        scan = _MethodScan(cls, always_guarded=meth.name.endswith(
            "_locked"))
        for stmt in meth.body:
            scan.visit(stmt)
        for attr, muts in scan.mutations.items():
            all_muts.setdefault(attr, []).extend(muts)
        for attr in scan.touched:
            methods_touching.setdefault(attr, set()).add(meth.name)

    out = []
    for attr, muts in sorted(all_muts.items()):
        guarded = [m for m in muts if m[0]]
        unguarded = [m for m in muts if not m[0]]
        if guarded and unguarded:
            out.append(make_finding(
                f"{PASS}:mixed-guard:{cls.qualname}.{attr}",
                f"{cls.qualname}.{attr} is mutated under "
                f"{sorted(cls.locks)} AND outside it "
                f"(unguarded at line {unguarded[0][1]})",
                rel, unguarded[0][1]))
            continue
        rmw_unguarded = [m for m in unguarded if m[2]]
        if rmw_unguarded and len(methods_touching.get(attr, ())) >= 2:
            out.append(make_finding(
                f"{PASS}:unguarded-rmw:{cls.qualname}.{attr}",
                f"{cls.qualname}.{attr} has read-modify-write "
                f"mutations with no lock held, and is accessed from "
                f"{len(methods_touching[attr])} methods of a "
                f"thread-spawning class", rel, rmw_unguarded[0][1]))
    return out
