"""Pass 1 — lock-order analysis.

Extracts every ``with <lock>:`` nesting across the package, builds the
cross-module lock-acquisition graph (edges: lock A held while lock B is
acquired, including one level of same-class method-call expansion), and
reports

- **cycles** in the graph (two code paths acquiring the same pair of
  locks in opposite order can deadlock), and
- **re-acquisition of a non-reentrant** ``threading.Lock`` — directly
  nested, or via a same-class method call made while the lock is held
  (a guaranteed self-deadlock on the path).

Resolution is conservative: a lock expression that can't be bound to a
unique definition (e.g. ``other._lock`` where many classes define
``_lock``) contributes no edges rather than speculative ones.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.analysis._astutil import (ClassInfo, LockIndex,
                                                LockRef, collect_classes,
                                                collect_module_locks,
                                                functions_in,
                                                iter_py_files,
                                                module_name, parse_file,
                                                with_lock_exprs)

PASS = "lock_order"


class _FuncScan(ast.NodeVisitor):
    """Per-function walk tracking the stack of held locks."""

    def __init__(self, index: LockIndex, cls: Optional[ClassInfo],
                 module: str, relpath: str):
        self.index = index
        self.cls = cls
        self.module = module
        self.relpath = relpath
        self.held: List[LockRef] = []
        #: (outer_id, inner_id) -> (file, line)
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        #: direct lock ids this function acquires
        self.acquired: Dict[str, str] = {}
        #: self-method calls made while holding locks:
        #: (callee_name, tuple(held ids), line)
        self.calls_held: List[Tuple[str, Tuple[str, ...], int]] = []
        self.reacquires: List[Tuple[str, int]] = []

    def visit_With(self, node: ast.With) -> None:
        refs = []
        for expr in with_lock_exprs(node):
            ref = self.index.resolve(expr, self.cls, self.module)
            if ref is None:
                continue
            refs.append(ref)
            self.acquired.setdefault(ref.id, ref.kind)
            for outer in self.held:
                if outer.id != ref.id:
                    self.edges.setdefault(
                        (outer.id, ref.id), (self.relpath, node.lineno))
            if any(h.id == ref.id for h in self.held) \
                    and not ref.reentrant():
                self.reacquires.append((ref.id, node.lineno))
        self.held.extend(refs)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(refs):len(self.held)]

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (self.held and isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"):
            self.calls_held.append(
                (f.attr, tuple(h.id for h in self.held), node.lineno))
        self.generic_visit(node)

    # nested defs (thread targets, closures) run on other stacks — the
    # enclosing function's held set must not leak into them
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _scan_function(fn: ast.FunctionDef, index: LockIndex,
                   cls: Optional[ClassInfo], module: str,
                   relpath: str) -> _FuncScan:
    scan = _FuncScan(index, cls, module, relpath)
    for stmt in fn.body:
        scan.visit(stmt)
    return scan


def collect_edges(root: str) -> Set[Tuple[str, str]]:
    """The static acquisition-order edge set (outer_id, inner_id) —
    the same graph ``analyze`` reports cycles on, exposed so the
    runtime sanitizer's lock witness can diff observed orders against
    it at shutdown."""
    index = LockIndex()
    parsed = []
    for rel, ap in iter_py_files(root):
        tree = parse_file(ap)
        if tree is None:
            continue
        mod = module_name(rel)
        cl = collect_classes(tree, mod)
        parsed.append((rel, mod, tree, cl))
        for c in cl:
            index.add_class(c)
        index.add_module_globals(mod, collect_module_locks(tree, mod))
    edges: Set[Tuple[str, str]] = set()
    for rel, mod, tree, cl in parsed:
        for cls in cl:
            scans = {m.name: _scan_function(m, index, cls, mod, rel)
                     for m in cls.methods()}
            for scan in scans.values():
                edges.update(scan.edges)
            for scan in scans.values():
                for callee, held_ids, _line in scan.calls_held:
                    target = scans.get(callee)
                    if target is None:
                        continue
                    for inner_id in target.acquired:
                        for outer_id in held_ids:
                            if outer_id != inner_id:
                                edges.add((outer_id, inner_id))
        for fn in (n for n in tree.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            edges.update(_scan_function(fn, index, None, mod, rel).edges)
    return edges


def analyze(root: str, make_finding) -> List:
    """Run the pass over every .py under ``root``. ``make_finding`` is
    the orchestrator's Finding factory: (key, message, file, line)."""
    files = [(rel, ap) for rel, ap in iter_py_files(root)]
    trees: Dict[str, ast.Module] = {}
    classes: Dict[str, List[ClassInfo]] = {}
    index = LockIndex()
    for rel, ap in files:
        tree = parse_file(ap)
        if tree is None:
            continue
        mod = module_name(rel)
        trees[rel] = tree
        cl = collect_classes(tree, mod)
        classes[rel] = cl
        for c in cl:
            index.add_class(c)
        index.add_module_globals(mod, collect_module_locks(tree, mod))

    findings = []
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    #: per class: method name -> scan (for one-level call expansion)
    for rel, tree in trees.items():
        mod = module_name(rel)
        for cls in classes[rel]:
            scans: Dict[str, _FuncScan] = {}
            for meth in cls.methods():
                scan = _scan_function(meth, index, cls, mod, rel)
                scans[meth.name] = scan
                edges.update(scan.edges)
                for lock_id, line in scan.reacquires:
                    findings.append(make_finding(
                        f"{PASS}:reacquire:{lock_id}:{cls.name}."
                        f"{meth.name}",
                        f"non-reentrant lock {lock_id} re-acquired "
                        f"inside its own with-block in "
                        f"{cls.qualname}.{meth.name}", rel, line))
            # one level of same-class call expansion: m holds L and
            # calls self.n(); n acquires M -> edge L->M (and L==M on a
            # plain Lock is a self-deadlock)
            for mname, scan in scans.items():
                for callee, held_ids, line in scan.calls_held:
                    target = scans.get(callee)
                    if target is None:
                        continue
                    for inner_id, inner_kind in target.acquired.items():
                        for outer_id in held_ids:
                            if outer_id == inner_id:
                                if inner_kind == "Lock":
                                    findings.append(make_finding(
                                        f"{PASS}:reacquire-via-call:"
                                        f"{inner_id}:{cls.name}."
                                        f"{mname}->{callee}",
                                        f"{cls.qualname}.{mname} holds "
                                        f"{inner_id} and calls self."
                                        f"{callee}() which re-acquires "
                                        f"it (non-reentrant: "
                                        f"self-deadlock)", rel, line))
                            else:
                                edges.setdefault(
                                    (outer_id, inner_id), (rel, line))
            # module-level functions get edge extraction too
        for fn in (n for n in tree.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))):
            scan = _scan_function(fn, index, None, mod, rel)
            edges.update(scan.edges)
            for lock_id, line in scan.reacquires:
                findings.append(make_finding(
                    f"{PASS}:reacquire:{lock_id}:{fn.name}",
                    f"non-reentrant lock {lock_id} re-acquired inside "
                    f"its own with-block in {mod}.{fn.name}",
                    rel, line))

    findings.extend(_cycle_findings(edges, make_finding))
    return findings


def _cycle_findings(edges: Dict[Tuple[str, str], Tuple[str, int]],
                    make_finding) -> List:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    sccs = _tarjan(graph)
    out = []
    for scc in sccs:
        if len(scc) < 2:
            continue
        cyc = sorted(scc)
        # anchor the finding at one edge inside the cycle
        loc = next((edges[(a, b)] for a in cyc for b in cyc
                    if (a, b) in edges), ("", 0))
        out.append(make_finding(
            "lock_order:cycle:" + "+".join(cyc),
            "lock acquisition cycle (potential deadlock): "
            + " -> ".join(cyc), loc[0], loc[1]))
    return out


def _tarjan(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC (the graph is tiny, but recursion limits
    are not worth risking inside a test gate)."""
    idx: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for start in graph:
        if start in idx:
            continue
        work = [(start, iter(sorted(graph[start])))]
        idx[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in idx:
                    idx[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], idx[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == idx[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)
    return sccs
