"""Shared AST plumbing for the raylint passes.

Everything here is deliberately std-lib only (``ast`` + ``os``): the
analyzer runs inside the tier-1 gate, so it must import in milliseconds
and carry zero dependency risk. Resolution is heuristic but HONEST —
when a lock expression can't be bound to a unique definition it is
skipped, never guessed, so the passes under-approximate rather than
invent cross-module edges.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

#: attribute/constructor names that create a lock-ish object
LOCK_FACTORIES = {"Lock": "Lock", "RLock": "RLock",
                  "Condition": "Condition", "Semaphore": "Semaphore",
                  "BoundedSemaphore": "Semaphore"}

#: container methods that mutate in place (shared-state pass)
MUTATING_METHODS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "add", "clear", "update",
    "setdefault", "sort", "reverse",
}


def iter_py_files(root: str) -> Iterator[Tuple[str, str]]:
    """Yield (relpath, abspath) for every .py under ``root``, skipping
    caches and the analyzer's own fixtures."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git",
                                          "fixtures"))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                ap = os.path.join(dirpath, fn)
                yield os.path.relpath(ap, root), ap


def parse_file(path: str) -> Optional[ast.Module]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None


def module_name(relpath: str) -> str:
    return relpath[:-3].replace(os.sep, ".")


def _is_lock_factory(call: ast.AST) -> Optional[str]:
    """'Lock' / 'RLock' / 'Condition' if ``call`` constructs one.

    Sees through the runtime sanitizer's witness wrapper —
    ``wrap_lock(threading.Lock(), "id")`` still DEFINES a Lock, and
    losing that binding would silently drop the lock (and every edge
    through it) from all static passes."""
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name == "wrap_lock" and call.args:
        return _is_lock_factory(call.args[0])
    if name in LOCK_FACTORIES:
        return LOCK_FACTORIES[name]
    return None


@dataclass
class ClassInfo:
    module: str
    name: str
    node: ast.ClassDef
    #: self-attribute name -> lock kind ("Lock" | "RLock" | "Condition")
    locks: Dict[str, str] = field(default_factory=dict)
    spawns_threads: bool = False

    @property
    def qualname(self) -> str:
        return f"{self.module}.{self.name}"

    def lock_id(self, attr: str) -> str:
        return f"{self.qualname}.{attr}"

    def methods(self) -> Iterator[ast.FunctionDef]:
        for stmt in self.node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield stmt


def collect_classes(tree: ast.Module, module: str) -> List[ClassInfo]:
    """Top-level classes with their lock attributes and whether they
    spawn threads (``threading.Thread(...)`` anywhere in a method)."""
    out = []
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        info = ClassInfo(module, node.name, node)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                kind = _is_lock_factory(sub.value)
                if kind:
                    for tgt in sub.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            info.locks[tgt.attr] = kind
            elif isinstance(sub, ast.Call):
                f = sub.func
                if ((isinstance(f, ast.Attribute) and f.attr == "Thread")
                        or (isinstance(f, ast.Name) and f.id == "Thread")):
                    info.spawns_threads = True
        out.append(info)
    return out


def collect_module_locks(tree: ast.Module, module: str) -> Dict[str, str]:
    """Module-level ``X = threading.Lock()`` globals: name -> kind."""
    out = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            kind = _is_lock_factory(node.value)
            if kind:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = kind
    return out


@dataclass
class LockRef:
    id: str     # "module.Class.attr" or "module.global"
    kind: str   # Lock | RLock | Condition | Semaphore

    def reentrant(self) -> bool:
        return self.kind == "RLock"


class LockIndex:
    """Repo-wide lock registry: resolves a ``with <expr>:`` expression
    to a unique lock definition, or to None when ambiguous."""

    def __init__(self) -> None:
        #: attr name -> [(lock_id, kind)] across every class
        self.by_attr: Dict[str, List[Tuple[str, str]]] = {}
        #: module -> {global name -> kind}
        self.module_globals: Dict[str, Dict[str, str]] = {}

    def add_class(self, info: ClassInfo) -> None:
        for attr, kind in info.locks.items():
            self.by_attr.setdefault(attr, []).append(
                (info.lock_id(attr), kind))

    def add_module_globals(self, module: str,
                           locks: Dict[str, str]) -> None:
        self.module_globals[module] = locks

    def resolve(self, expr: ast.AST, cls: Optional[ClassInfo],
                module: str) -> Optional[LockRef]:
        """Bind a with-item expression to a lock definition.

        self.X        -> this class's lock X (exact)
        bare NAME     -> this module's global lock (exact)
        other.X       -> the unique class defining lock attr X, if ONE
                         class in the repo does (else unresolvable)
        """
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if (isinstance(expr.value, ast.Name)
                    and expr.value.id == "self" and cls is not None
                    and attr in cls.locks):
                return LockRef(cls.lock_id(attr), cls.locks[attr])
            defs = self.by_attr.get(attr, [])
            if len(defs) == 1:
                return LockRef(defs[0][0], defs[0][1])
            return None
        if isinstance(expr, ast.Name):
            kind = self.module_globals.get(module, {}).get(expr.id)
            if kind:
                return LockRef(f"{module}.{expr.id}", kind)
        return None


def with_lock_exprs(node: ast.With) -> List[ast.AST]:
    """The context expressions of a with-statement that LOOK lock-like
    (named *lock*, *_cv*, *cond*, or a bare attribute); non-lock
    context managers (open(), suppress()...) are never candidates."""
    out = []
    for item in node.items:
        e = item.context_expr
        name = None
        if isinstance(e, ast.Attribute):
            name = e.attr
        elif isinstance(e, ast.Name):
            name = e.id
        if name is None:
            continue
        low = name.lower()
        if "lock" in low or "cv" in low or "cond" in low:
            out.append(e)
    return out


def functions_in(node: ast.AST) -> Iterator[ast.FunctionDef]:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield sub


def find_function(tree: ast.Module,
                  qualname: str) -> List[ast.FunctionDef]:
    """'Class.method' or 'func' -> matching FunctionDef nodes."""
    parts = qualname.split(".")
    if len(parts) == 1:
        return [n for n in tree.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name == parts[0]]
    out = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == parts[0]:
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name == parts[1]:
                    out.append(sub)
    return out


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
