"""Pass 5 — registry drift.

Two registries pair a STRING namespace with code that must stay in
lockstep:

- **state verbs**: the ray:// server only forwards verbs allowlisted in
  ``client.py``'s ``_STATE_VERBS`` frozenset, and ``util/state.py``
  defines the implementations (``@_client_dispatch``). A verb defined
  but not allowlisted silently 403s over ray://; a verb allowlisted but
  not defined AttributeErrors at dispatch.
- **Prometheus metrics**: names emitted by ``_private/metrics.py`` (and
  the task-event histograms it inlines from ``task_events.py``) form
  the de-facto registry; every emitted name must be documented in
  README.md, and every documented name must still be emitted (stale
  docs were how the retired ``ray_tpu_log_bytes_written_total`` alias
  lingered). README tokens support ``{a,b}`` brace alternation.
- **chaos fault sites**: ``chaos.py``'s ``_SITE_KINDS`` dict is the
  injection-site registry; README's chaos section documents each site
  as a backticked name followed by a parenthesized kinds note. A site
  added to the code but not the docs is invisible to users writing
  fault plans; a documented site the controller rejects fails their
  plan at arm() (this is how the ``sched`` vs ``sched_tick`` naming
  drift and the missing ``head`` site were caught).

Emitted names are collected from ``emit("name", ...)`` first args,
``ray_tpu_*`` strings inside tuple/list literals (the counter tables),
and ``# HELP``/``# TYPE`` lines inside string constants — thread names
and other stray strings never match those shapes.
"""

from __future__ import annotations

import ast
import itertools
import os
import re
from typing import Dict, List, Optional, Sequence, Set

from ray_tpu._private.analysis._astutil import (const_str, find_function,
                                                parse_file)

PASS = "registry"

_METRIC_RE = re.compile(r"^ray_tpu_[a-z0-9_]+$")
_HELP_TYPE_RE = re.compile(r"#\s*(?:HELP|TYPE)\s+(ray_tpu_[a-z0-9_]+)")
_DOC_TOKEN_RE = re.compile(r"ray_tpu_[a-z0-9_{},]+")


# ---------------------------------------------------------------------------
# state verbs
# ---------------------------------------------------------------------------

def collect_allowlist(tree: ast.Module,
                      var: str = "_STATE_VERBS") -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == var
                   for t in node.targets):
            continue
        for sub in ast.walk(node.value):
            s = const_str(sub)
            if s:
                out.add(s)
    return out


def collect_dispatch_defs(tree: ast.Module,
                          decorator: str = "_client_dispatch"
                          ) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            name = dec.attr if isinstance(dec, ast.Attribute) else (
                dec.id if isinstance(dec, ast.Name) else None)
            if name == decorator:
                out[node.name] = node.lineno
    return out


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

def collect_emitted_metrics(tree: ast.Module, source: str) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "emit" and node.args):
            s = const_str(node.args[0])
            if s and _METRIC_RE.match(s):
                out.add(s)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                s = const_str(e)
                if s and _METRIC_RE.match(s):
                    out.add(s)
    out.update(_HELP_TYPE_RE.findall(source))
    return out


def expand_doc_token(token: str) -> List[str]:
    """``ray_tpu_sched_locality_{hit,miss}_total`` -> both names."""
    parts: List[List[str]] = []
    for frag in re.split(r"(\{[^}]*\})", token):
        if frag.startswith("{") and frag.endswith("}"):
            parts.append(frag[1:-1].split(","))
        elif frag:
            parts.append([frag])
    return ["".join(p) for p in itertools.product(*parts)] if parts \
        else []


def collect_documented_metrics(readme: str) -> Dict[str, str]:
    """expanded metric name -> the doc token it came from."""
    out: Dict[str, str] = {}
    for token in _DOC_TOKEN_RE.findall(readme):
        for name in expand_doc_token(token):
            if _METRIC_RE.match(name):
                out[name] = token
    return out


# ---------------------------------------------------------------------------
# chaos fault sites
# ---------------------------------------------------------------------------

# a documented site row reads like:  `worker` (SIGKILL),  — a backticked
# bare name immediately followed by a parenthesized kinds note. Tokens
# with dots (`ray_tpu.chaos`) or without the "(" never match.
_CHAOS_SITE_DOC_RE = re.compile(r"`([a-z][a-z0-9_]*)`\s*\(")
_CHAOS_HEADING_RE = re.compile(r"^#+\s.*chaos", re.IGNORECASE | re.MULTILINE)


def collect_chaos_sites(tree: ast.Module,
                        var: str = "_SITE_KINDS") -> Dict[str, int]:
    """site name -> lineno, from the ``_SITE_KINDS`` dict literal.
    Matches both plain and annotated assignments — the real registry
    is annotated (``_SITE_KINDS: Dict[...] = {...}``), and an
    Assign-only walk silently disabled this whole check."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == var
                   for t in targets):
            continue
        if isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                s = const_str(k)
                if s:
                    out[s] = k.lineno
    return out


def collect_documented_sites(readme: str) -> Set[str]:
    """Backticked site names from README's chaos section (heading
    containing 'chaos' up to the next heading)."""
    m = _CHAOS_HEADING_RE.search(readme)
    if m is None:
        return set()
    section = readme[m.end():]
    nxt = re.search(r"^#+\s", section, re.MULTILINE)
    if nxt is not None:
        section = section[:nxt.start()]
    return set(_CHAOS_SITE_DOC_RE.findall(section))


# ---------------------------------------------------------------------------
# pass entry point
# ---------------------------------------------------------------------------

def analyze(root: str, make_finding,
            client_relpath: str = "_private/client.py",
            state_relpath: str = "util/state.py",
            metrics_relpaths: Sequence[str] = ("_private/metrics.py",
                                               "_private/task_events.py"),
            readme_path: Optional[str] = None,
            dispatch_exempt: Sequence[str] = (),
            chaos_relpath: str = "_private/chaos.py") -> List:
    findings: List = []

    client_tree = parse_file(os.path.normpath(
        os.path.join(root, client_relpath)))
    state_tree = parse_file(os.path.normpath(
        os.path.join(root, state_relpath)))
    if client_tree is not None and state_tree is not None:
        allow = collect_allowlist(client_tree)
        defs = collect_dispatch_defs(state_tree)
        for verb in sorted(set(defs) - allow - set(dispatch_exempt)):
            findings.append(make_finding(
                f"{PASS}:verb-unlisted:{verb}",
                f"state verb {verb!r} is defined in {state_relpath} but "
                f"missing from the ray:// allowlist in "
                f"{client_relpath}", state_relpath, defs[verb]))
        for verb in sorted(allow - set(defs)):
            findings.append(make_finding(
                f"{PASS}:verb-undefined:{verb}",
                f"state verb {verb!r} is allowlisted over ray:// but "
                f"{state_relpath} defines no such function",
                client_relpath, 0))

    emitted: Set[str] = set()
    for rel in metrics_relpaths:
        ap = os.path.normpath(os.path.join(root, rel))
        try:
            with open(ap, "r", encoding="utf-8") as f:
                source = f.read()
        except OSError:
            continue
        tree = parse_file(ap)
        if tree is None:
            continue
        emitted |= collect_emitted_metrics(tree, source)

    if readme_path is None:
        readme_path = os.path.normpath(
            os.path.join(root, "..", "README.md"))
    try:
        with open(readme_path, "r", encoding="utf-8") as f:
            readme = f.read()
    except OSError:
        readme = ""
    if readme:
        documented = collect_documented_metrics(readme)
        for name in sorted(emitted - set(documented)):
            findings.append(make_finding(
                f"{PASS}:metric-undocumented:{name}",
                f"metric {name!r} is emitted but not documented in "
                f"README.md", metrics_relpaths[0], 0))
        stale_tokens = {tok for name, tok in documented.items()
                        if name not in emitted}
        live_tokens = {tok for name, tok in documented.items()
                       if name in emitted}
        for tok in sorted(stale_tokens - live_tokens):
            findings.append(make_finding(
                f"{PASS}:metric-phantom:{tok}",
                f"README documents metric {tok!r} but nothing emits "
                f"it", "README.md", 0))

    chaos_tree = parse_file(os.path.normpath(
        os.path.join(root, chaos_relpath)))
    if chaos_tree is not None and readme:
        sites = collect_chaos_sites(chaos_tree)
        documented_sites = collect_documented_sites(readme)
        if sites and documented_sites:
            for site in sorted(set(sites) - documented_sites):
                findings.append(make_finding(
                    f"{PASS}:chaos-site-undocumented:{site}",
                    f"chaos fault site {site!r} is registered in "
                    f"{chaos_relpath} (_SITE_KINDS) but README's chaos "
                    f"section does not document it",
                    chaos_relpath, sites[site]))
            for site in sorted(documented_sites - set(sites)):
                findings.append(make_finding(
                    f"{PASS}:chaos-site-phantom:{site}",
                    f"README's chaos section documents fault site "
                    f"{site!r} but {chaos_relpath} (_SITE_KINDS) does "
                    f"not register it — a fault plan naming it fails "
                    f"at arm()", "README.md", 0))
    return findings
