"""Pass 6 — ObjectRef lifecycle conformance.

``ObjectRef.__init__`` self-registers a local reference unless built
with ``_register=False`` — a WEAK ref that holds no refcount and whose
object can be freed underneath it. The repo's contract (see
object_ref.py / ref_counting.py) is that weak refs stay ephemeral:
built, handed to one call, dropped. Three drift patterns are flagged:

- **weak-escape**: a weak ref (or a container it was put in) is
  returned from the function or stored on ``self`` without
  re-registration. The escapee looks like a live handle but the store
  may already have reclaimed the object. Re-registration is signalled
  the way ``Worker.submit_task_batch`` does it — a ``X._weak = False``
  assignment anywhere in the function exempts it (the counting happened
  out-of-band, e.g. via ``register_submit_batch``).
- **double-release**: the same name released twice on one straight-line
  path (``remove_local_reference`` / ``defer_unref``) with no
  rebinding in between — the second call decrements someone else's
  refcount.
- **get-after-free**: a released name handed to a blocking
  ``worker.get(...)`` later on the same path — the classic
  use-after-free shape, one rename away from returning garbage.

Straight-line means SAME statement list: branches are separate paths
and loops rebind their targets, so both are skipped — the pass
under-approximates rather than guessing control flow.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ray_tpu._private.analysis._astutil import (iter_py_files,
                                                module_name, parse_file)

PASS = "ref_lifecycle"

#: call attrs that release a ref held for NAME
_RELEASE_ATTRS = {"remove_local_reference", "defer_unref"}


def _is_weak_ref_call(node: ast.AST) -> bool:
    """``ObjectRef(..., _register=False)``."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name != "ObjectRef":
        return False
    for kw in node.keywords:
        if (kw.arg == "_register"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False):
            return True
    return False


def _contains_weak_call(node: ast.AST) -> bool:
    """The expression builds weak refs somewhere inside (covers list
    comprehensions and literal lists of ``ObjectRef(..)`` calls)."""
    return any(_is_weak_ref_call(sub) for sub in ast.walk(node))


def _names_in(node: ast.AST) -> Set[str]:
    return {sub.id for sub in ast.walk(node) if isinstance(sub, ast.Name)}


def _direct_names(expr: ast.AST) -> Set[str]:
    """Names the expression hands over AS VALUES: a bare name or a
    container literal of names. A name appearing as a call ARGUMENT is
    consumption inside this scope (``return worker.wait(refs, ...)``),
    not an escape of the ref itself."""
    if isinstance(expr, ast.Name):
        return {expr.id}
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        out: Set[str] = set()
        for e in expr.elts:
            out |= _direct_names(e)
        return out
    return set()


def _walk_local(fn: ast.AST):
    """ast.walk that stays in ``fn``'s own scope — nested defs are
    separate scopes and are analyzed on their own."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _qualname_functions(tree: ast.Module):
    """Yield (qualname, FunctionDef) for every function, nested included."""
    def walk(node, prefix):
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{sub.name}" if prefix else sub.name
                yield q, sub
                yield from walk(sub, q)
            elif isinstance(sub, ast.ClassDef):
                q = f"{prefix}.{sub.name}" if prefix else sub.name
                yield from walk(sub, q)
            else:
                yield from walk(sub, prefix)
    yield from walk(tree, "")


def _check_weak_escape(qual: str, fn: ast.FunctionDef, mod: str,
                       rel: str, make_finding) -> List:
    # names bound (directly or by alias) to weak refs / containers of them
    weak: Dict[str, int] = {}
    reregistered = False
    for sub in _walk_local(fn):
        if isinstance(sub, ast.Assign):
            # X._weak = False anywhere = counting happened out-of-band
            for tgt in sub.targets:
                if (isinstance(tgt, ast.Attribute) and tgt.attr == "_weak"
                        and isinstance(sub.value, ast.Constant)
                        and sub.value.value is False):
                    reregistered = True
            if _contains_weak_call(sub.value):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        weak.setdefault(tgt.id, sub.lineno)
            elif (isinstance(sub.value, ast.Name)
                    and sub.value.id in weak):            # alias: Y = X
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        weak.setdefault(tgt.id, sub.lineno)
    if reregistered or not weak:
        return []

    # one-level containment: Y.append(X) / Y.extend([X...]) taints Y
    for sub in _walk_local(fn):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("append", "extend", "add")
                and isinstance(sub.func.value, ast.Name)):
            if any(n in weak for a in sub.args for n in _names_in(a)):
                weak.setdefault(sub.func.value.id, sub.lineno)

    out = []
    flagged: Set[str] = set()
    for sub in _walk_local(fn):
        escaped: Set[str] = set()
        line = getattr(sub, "lineno", fn.lineno)
        if isinstance(sub, ast.Return) and sub.value is not None:
            escaped = _direct_names(sub.value) & set(weak)
        elif isinstance(sub, ast.Assign):
            # self.attr = <a weak name or a container literal of them>
            if any(isinstance(t, ast.Attribute)
                   and isinstance(t.value, ast.Name)
                   and t.value.id == "self" for t in sub.targets):
                escaped = _direct_names(sub.value) & set(weak)
        for name in sorted(escaped - flagged):
            flagged.add(name)
            out.append(make_finding(
                f"{PASS}:weak-escape:{mod}.{qual}:{name}",
                f"{mod}.{qual} lets weak ObjectRef '{name}' "
                f"(_register=False, line {weak[name]}) escape the "
                f"function without re-registration — the object can be "
                f"freed under the escaped handle", rel, line))
    return out


def _release_target(stmt: ast.stmt) -> Optional[str]:
    """NAME if ``stmt`` is ``...remove_local_reference(NAME)`` etc."""
    if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
        return None
    call = stmt.value
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _RELEASE_ATTRS
            and len(call.args) >= 1 and isinstance(call.args[0], ast.Name)):
        return call.args[0].id
    return None


def _get_call_args(stmt: ast.stmt) -> Set[str]:
    """Names passed to a worker-style blocking ``get`` in ``stmt``."""
    out: Set[str] = set()
    for sub in ast.walk(stmt):
        if not (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "get"):
            continue
        base = sub.func.value
        base_name = base.id if isinstance(base, ast.Name) else (
            base.attr if isinstance(base, ast.Attribute) else None)
        if base_name not in ("worker", "_worker", "ray_tpu", "ray"):
            continue
        for a in sub.args:
            out |= _names_in(a)
    return out


def _check_release_paths(qual: str, fn: ast.FunctionDef, mod: str,
                         rel: str, make_finding) -> List:
    out = []

    def scan_block(stmts: List[ast.stmt]) -> None:
        released: Dict[str, int] = {}
        for stmt in stmts:
            # a rebinding makes the name a fresh ref again
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                tgts = (stmt.targets
                        if isinstance(stmt, ast.Assign) else [stmt.target])
                for t in tgts:
                    if isinstance(t, ast.Name):
                        released.pop(t.id, None)
            name = _release_target(stmt)
            if name is not None:
                if name in released:
                    out.append(make_finding(
                        f"{PASS}:double-release:{mod}.{qual}:{name}",
                        f"{mod}.{qual} releases ref '{name}' twice on "
                        f"the same path (first at line "
                        f"{released[name]}) — the second call "
                        f"decrements another holder's count",
                        rel, stmt.lineno))
                else:
                    released[name] = stmt.lineno
                continue
            for name in sorted(_get_call_args(stmt) & set(released)):
                out.append(make_finding(
                    f"{PASS}:get-after-free:{mod}.{qual}:{name}",
                    f"{mod}.{qual} passes '{name}' to a blocking get "
                    f"after releasing it at line {released[name]} — "
                    f"the object may already be reclaimed",
                    rel, stmt.lineno))
            # loop/branch bodies are separate paths: recurse with a
            # fresh released-set, don't thread state through them
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and sub:
                    scan_block(sub)
            for h in getattr(stmt, "handlers", ()):
                scan_block(h.body)

    scan_block(fn.body)
    return out


def analyze(root: str, make_finding) -> List:
    findings = []
    for rel, ap in iter_py_files(root):
        tree = parse_file(ap)
        if tree is None:
            continue
        mod = module_name(rel)
        for qual, fn in _qualname_functions(tree):
            findings.extend(
                _check_weak_escape(qual, fn, mod, rel, make_finding))
            findings.extend(
                _check_release_paths(qual, fn, mod, rel, make_finding))
    return findings
