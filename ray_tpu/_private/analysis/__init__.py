"""raylint + raysan — ray_tpu's framework-invariant analysis plane.

Eight AST passes over the whole package, each encoding an invariant the
repo's history shows drifts silently (see the per-pass module
docstrings): lock ordering, unguarded shared state, wire-protocol
conformance, knob consistency, registry drift, ObjectRef lifecycle,
closure-capture hygiene, and blocking calls in no-block contexts.

The static plane has a runtime mirror (``runtime_sanitizer``, armed by
``RAY_TPU_SANITIZE=1``): a lock-witness recorder diffed against
lock_order's static graph, a shm/ref leak ledger reported at shutdown,
and wire-message schema assertions compiled from wire_protocol's
channel table.

Findings carry **stable, line-free keys** (``pass:category:subject``)
so a checked-in ``baseline.json`` can suppress pre-existing violations
without going stale on every reformat; the tier-1 gate fails only on
findings whose key is not baselined. Run it:

    python -m ray_tpu lint            # human text, exit 1 on NEW findings
    python -m ray_tpu lint --json     # machine output
    python -m ray_tpu lint --update-baseline   # re-baseline the rest
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private.analysis import (blocking_calls, closure_capture,
                                       knobs, lock_order, ref_lifecycle,
                                       registry, shared_state,
                                       wire_protocol)

#: the package root the passes scan, resolved from this file
PACKAGE_ROOT = os.path.normpath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

PASSES = (("lock_order", lock_order.analyze),
          ("shared_state", shared_state.analyze),
          ("wire_protocol", wire_protocol.analyze),
          ("knobs", knobs.analyze),
          ("registry", registry.analyze),
          ("ref_lifecycle", ref_lifecycle.analyze),
          ("closure_capture", closure_capture.analyze),
          ("blocking_calls", blocking_calls.analyze))


@dataclass
class Finding:
    key: str        # stable, line-free: "pass:category:subject"
    message: str
    file: str
    line: int
    pass_id: str = ""


@dataclass
class Report:
    findings: List[Finding]
    new: List[Finding]
    baselined: List[Finding]
    stale_suppressions: List[str]
    durations: Dict[str, float] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.new

    def to_json(self) -> dict:
        def row(f: Finding) -> dict:
            return {"key": f.key, "message": f.message, "file": f.file,
                    "line": f.line, "pass": f.pass_id}
        return {
            "ok": self.ok,
            "new": [row(f) for f in self.new],
            "baselined": [row(f) for f in self.baselined],
            "stale_suppressions": list(self.stale_suppressions),
            "durations_s": {k: round(v, 4)
                            for k, v in self.durations.items()},
        }

    def render_text(self) -> str:
        lines = []
        for f in self.new:
            loc = f"{f.file}:{f.line}" if f.file else "<package>"
            lines.append(f"NEW  [{f.pass_id}] {loc}: {f.message}")
            lines.append(f"     key: {f.key}")
        if self.baselined:
            lines.append(f"{len(self.baselined)} baselined finding(s) "
                         f"suppressed (analysis/baseline.json)")
        for key in self.stale_suppressions:
            lines.append(f"STALE suppression (no longer fires): {key}")
        total = sum(self.durations.values())
        lines.append(
            f"raylint: {len(self.new)} new, {len(self.baselined)} "
            f"baselined, {len(self.stale_suppressions)} stale "
            f"suppression(s) in {total:.2f}s")
        return "\n".join(lines)


def load_baseline(path: Optional[str] = None) -> List[str]:
    try:
        with open(path or BASELINE_PATH, "r", encoding="utf-8") as f:
            data = json.load(f)
        return list(data.get("suppress", []))
    except (OSError, ValueError):
        return []


def save_baseline(keys: List[str], path: Optional[str] = None) -> None:
    with open(path or BASELINE_PATH, "w", encoding="utf-8") as f:
        json.dump({"comment": "raylint suppressions: stable finding "
                              "keys for pre-existing, reviewed "
                              "violations. Remove entries as the code "
                              "they cover is fixed.",
                   "suppress": sorted(keys)}, f, indent=2)
        f.write("\n")


def run_all(root: Optional[str] = None,
            baseline_path: Optional[str] = None,
            passes=PASSES) -> Report:
    root = root or PACKAGE_ROOT
    findings: List[Finding] = []
    durations: Dict[str, float] = {}
    for pass_id, fn in passes:
        def make_finding(key, message, file, line, _p=pass_id):
            return Finding(key=key, message=message, file=file,
                           line=line, pass_id=_p)
        t0 = time.perf_counter()
        findings.extend(fn(root, make_finding))
        durations[pass_id] = time.perf_counter() - t0

    suppress = set(load_baseline(baseline_path))
    seen_keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in suppress]
    baselined = [f for f in findings if f.key in suppress]
    stale = sorted(suppress - seen_keys)
    new.sort(key=lambda f: (f.pass_id, f.file, f.key))
    return Report(findings=findings, new=new, baselined=baselined,
                  stale_suppressions=stale, durations=durations)
