"""Pass 4 — knob consistency.

Every knob registered in ``_private/config.py`` must be

- **overridable from the environment** — satisfied by construction:
  ``ConfigRegistry.define`` applies ``RAY_TPU_<NAME>`` itself, so a
  knob cannot lack an override. The pass still verifies the knob name
  is a valid env-suffix identifier (lowercase, no dashes) so the
  override actually resolves.
- **read somewhere** — at least one site in the package (outside
  config.py itself) reads it, via attribute access
  (``GLOBAL_CONFIG.task_events_max``), ``.get("name")`` /
  ``.entry("name")`` / ``set(...)`` string use, or an
  ``RAY_TPU_<NAME>`` env literal. A knob nobody reads is dead — the
  ``log_dir`` class of bug (PR 3).
- **documented** — mentioned in README.md as an exact token. Plain
  substring matching had a false-negative class: an undocumented knob
  whose name is a SUBSTRING of a documented one (``tick_interval_s``
  riding on ``sched_tick_interval_s``) passed silently. The README is
  tokenized instead, with two conveniences: ``RAY_TPU_<NAME>`` env
  spellings count as documenting ``<name>``, and brace-expanded
  doc shorthand (``sched_max_{edges,nodes}``) counts for every
  expansion — same grammar registry.expand_doc_token uses.
"""

from __future__ import annotations

import ast
import itertools
import os
import re
from typing import Dict, List, Optional, Set

from ray_tpu._private.analysis._astutil import (const_str,
                                                iter_py_files,
                                                parse_file)

PASS = "knob"

_DEFINE_CALLEES = {"_d", "define"}


def collect_knobs(config_tree: ast.Module) -> Dict[str, int]:
    """knob name -> definition line, from ``_d("name", ...)`` /
    ``REG.define("name", ...)`` calls."""
    out: Dict[str, int] = {}
    for node in ast.walk(config_tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name not in _DEFINE_CALLEES:
            continue
        knob = const_str(node.args[0])
        if knob:
            out[knob] = node.lineno
    return out


def collect_reads(root: str, config_relpath: str,
                  knobs: Set[str]) -> Dict[str, int]:
    """knob -> count of read sites across the package."""
    env_names = {f"RAY_TPU_{k.upper()}": k for k in knobs}
    reads: Dict[str, int] = {k: 0 for k in knobs}
    for rel, ap in iter_py_files(root):
        if rel == config_relpath:
            continue
        tree = parse_file(ap)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and node.attr in knobs:
                reads[node.attr] += 1
            elif isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                if node.value in knobs:
                    reads[node.value] += 1
                elif node.value in env_names:
                    reads[env_names[node.value]] += 1
    return reads


def _expand_braces(tok: str) -> List[str]:
    """``a_{b,c}_d`` -> [``a_b_d``, ``a_c_d``] (no nesting)."""
    parts = re.split(r"(\{[^{}]*\})", tok)
    if len(parts) == 1:
        return [tok]
    pools = [p[1:-1].split(",") if p.startswith("{") else [p]
             for p in parts if p]
    return ["".join(combo) for combo in itertools.product(*pools)]


def readme_knob_tokens(readme: str) -> Set[str]:
    """Every exact name the README documents: word-ish tokens (the
    charset includes ``{},`` so brace shorthand survives markdown
    splitting, and spans table-cell line wraps since the regex runs
    over the whole text), brace-expanded, with ``RAY_TPU_X`` env
    spellings lowered to the knob name ``x``."""
    out: Set[str] = set()
    for raw in re.findall(r"[A-Za-z0-9_{},]+", readme):
        for tok in _expand_braces(raw):
            tok = tok.strip(",")
            if not tok:
                continue
            out.add(tok)
            if tok.startswith("RAY_TPU_"):
                out.add(tok[len("RAY_TPU_"):].lower())
    return out


def analyze(root: str, make_finding,
            config_relpath: str = "_private/config.py",
            readme_path: Optional[str] = None) -> List:
    findings: List = []
    config_path = os.path.normpath(os.path.join(root, config_relpath))
    tree = parse_file(config_path)
    if tree is None:
        return findings
    knobs = collect_knobs(tree)
    if readme_path is None:
        readme_path = os.path.normpath(
            os.path.join(root, "..", "README.md"))
    try:
        with open(readme_path, "r", encoding="utf-8") as f:
            readme = f.read()
    except OSError:
        readme = ""

    reads = collect_reads(root, config_relpath, set(knobs))
    documented = readme_knob_tokens(readme) if readme else set()
    for name, line in sorted(knobs.items()):
        if not re.fullmatch(r"[a-z][a-z0-9_]*", name):
            findings.append(make_finding(
                f"{PASS}:bad-name:{name}",
                f"knob {name!r} is not a lowercase identifier, so its "
                f"RAY_TPU_ env override cannot resolve",
                config_relpath, line))
        if reads.get(name, 0) == 0:
            findings.append(make_finding(
                f"{PASS}:dead:{name}",
                f"knob {name!r} is defined but never read anywhere in "
                f"the package", config_relpath, line))
        if readme and name not in documented:
            findings.append(make_finding(
                f"{PASS}:undocumented:{name}",
                f"knob {name!r} is not mentioned in README.md",
                config_relpath, line))
    return findings
