"""Object serialization with zero-copy out-of-band buffers.

Role of the reference's python/ray/_private/serialization.py: pickle
protocol 5 with out-of-band PickleBuffers so large numpy/jax arrays are
serialized as (metadata, raw buffer list) and can be placed in shared
memory or handed to the device without a copy. ObjectRefs found inside
values are recorded so the owner can track borrowers (reference:
ReferenceCounter borrower protocol).
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Callable, Dict, List, Tuple

import numpy as np


class SerializedObject:
    """Pickled metadata + out-of-band buffers; total_bytes is the store cost."""

    __slots__ = ("meta", "buffers", "contained_refs")

    def __init__(self, meta: bytes, buffers: List[memoryview], contained_refs: list):
        self.meta = meta
        self.buffers = buffers
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        return len(self.meta) + sum(len(b) for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten to one contiguous blob: [meta_len][meta][nbuf][len,buf]*."""
        out = io.BytesIO()
        out.write(len(self.meta).to_bytes(8, "little"))
        out.write(self.meta)
        out.write(len(self.buffers).to_bytes(4, "little"))
        for b in self.buffers:
            out.write(len(b).to_bytes(8, "little"))
            out.write(b)
        return out.getvalue()

    def framed_nbytes(self) -> int:
        """Size of the to_bytes() framing without materializing it."""
        return 8 + len(self.meta) + 4 + sum(8 + len(b) for b in self.buffers)

    def write_into(self, view: memoryview) -> int:
        """Write the framed form straight into a caller-provided buffer
        (the shm arena) — single copy, no intermediate blob."""
        off = 0

        def put(b: bytes | memoryview):
            nonlocal off
            n = len(b)
            view[off:off + n] = b
            off += n

        put(len(self.meta).to_bytes(8, "little"))
        put(self.meta)
        put(len(self.buffers).to_bytes(4, "little"))
        for b in self.buffers:
            put(len(b).to_bytes(8, "little"))
            put(b)
        return off

    @classmethod
    def frame_complete(cls, blob: memoryview | bytes) -> bool:
        """Whether `blob` holds a whole to_bytes() frame. Wire fetches
        must check this before from_bytes: memoryview slicing past the
        end silently yields SHORT buffers, so a truncated transfer
        would otherwise deserialize into corrupt data instead of being
        retried as a lost object."""
        view = memoryview(blob)
        total = len(view)
        if total < 8:
            return False
        off = 8 + int.from_bytes(view[:8], "little")
        if off + 4 > total:
            return False
        nbuf = int.from_bytes(view[off:off + 4], "little")
        off += 4
        for _ in range(nbuf):
            if off + 8 > total:
                return False
            off += 8 + int.from_bytes(view[off:off + 8], "little")
        return off <= total

    @classmethod
    def from_bytes(cls, blob: memoryview | bytes) -> "SerializedObject":
        view = memoryview(blob)
        meta_len = int.from_bytes(view[:8], "little")
        off = 8
        meta = bytes(view[off : off + meta_len])
        off += meta_len
        nbuf = int.from_bytes(view[off : off + 4], "little")
        off += 4
        buffers = []
        for _ in range(nbuf):
            blen = int.from_bytes(view[off : off + 8], "little")
            off += 8
            buffers.append(view[off : off + blen])
            off += blen
        return cls(meta, buffers, [])


_custom_serializers: Dict[type, Tuple[Callable, Callable]] = {}


def register_serializer(cls: type, *, serializer: Callable, deserializer: Callable):
    """ray.util.register_serializer equivalent."""
    _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: type):
    _custom_serializers.pop(cls, None)


class _Pickler(pickle.Pickler):
    def __init__(self, file, contained_refs: list):
        super().__init__(file, protocol=5, buffer_callback=self._buffer_cb)
        self._oob: List[memoryview] = []
        self._contained_refs = contained_refs

    def _buffer_cb(self, buf: pickle.PickleBuffer):
        self._oob.append(buf.raw())
        return False  # out-of-band

    def reducer_override(self, obj):
        from ray_tpu._private.object_ref import ObjectRef

        if type(obj) in _custom_serializers:
            ser, deser = _custom_serializers[type(obj)]
            return (_reconstruct_custom, (type(obj), ser(obj)))
        if isinstance(obj, ObjectRef):
            self._contained_refs.append(obj)
        return NotImplemented


def _reconstruct_custom(cls, payload):
    return _custom_serializers[cls][1](payload)


def serialize(value: Any) -> SerializedObject:
    contained_refs: list = []
    f = io.BytesIO()
    p = _Pickler(f, contained_refs)
    # jax arrays: move to host numpy once so the buffer is mmap-able
    value = _device_to_host(value)
    p.dump(value)
    return SerializedObject(f.getvalue(), p._oob, contained_refs)


def deserialize(obj: SerializedObject) -> Any:
    buffers = [pickle.PickleBuffer(b) for b in obj.buffers]
    return pickle.loads(obj.meta, buffers=buffers)


class _BufferAnchor(np.ndarray):
    """Weakref-able buffer-protocol re-exporter. Reconstructed views
    (numpy arrays, Arrow buffers — and anything sliced off them) keep
    their buffer EXPORTER alive through the C buffer protocol; plain
    memoryviews cannot take weakrefs, so re-exporting through this
    anchor is what lets a finalizer observe the true last-view death.
    An ndarray view (not a class with ``__buffer__``, which only
    Python 3.12+ honours) so the anchor exports the buffer protocol
    on every supported interpreter."""


def _anchor(buf) -> _BufferAnchor:
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    return np.frombuffer(mv, dtype=np.uint8).view(_BufferAnchor)


def deserialize_with_release(obj: SerializedObject,
                             release: Callable[[], None]) -> Any:
    """deserialize(), with `release()` called when the LAST object
    aliasing obj's out-of-band buffers is garbage-collected — including
    sub-views extracted later (an Arrow column taken off a Table, a
    numpy slice). Used by the shm store's zero-copy read path to hold
    the arena pin for exactly the views' lifetime."""
    import weakref

    if not obj.buffers:
        try:
            return deserialize(obj)  # plain pickle: nothing aliases
        finally:
            release()
    anchors = [_anchor(b) for b in obj.buffers]
    remaining = [len(anchors)]

    def _one_done():
        remaining[0] -= 1
        if remaining[0] == 0:
            release()

    for a in anchors:
        weakref.finalize(a, _one_done)
    rewrapped = SerializedObject(obj.meta,
                                 [memoryview(a) for a in anchors],
                                 obj.contained_refs)
    return deserialize(rewrapped)


def _device_to_host(value: Any) -> Any:
    """Convert jax.Array leaves to numpy (zero-copy when already on host)."""
    try:
        import jax
    except ImportError:  # pragma: no cover
        return value
    if isinstance(value, jax.Array):
        return np.asarray(value)
    if isinstance(value, tuple):
        return tuple(_device_to_host(v) for v in value)
    if isinstance(value, list):
        return [_device_to_host(v) for v in value]
    if isinstance(value, dict):
        return {k: _device_to_host(v) for k, v in value.items()}
    return value


# ---------------------------------------------------------------------------
# completion-envelope codec: batched worker -> owner results
# ---------------------------------------------------------------------------
# The worker's buffered completions (the ``_done_buf`` the pipe used to
# carry as per-message pickles) pack into one struct-framed envelope for
# the shared-memory completion ring. Item shapes are exactly the pipe
# messages: ("done", task_id, entries, (t0, t1)) with entries of
# ("inline", blob) | ("shm", offset, nbytes), and
# ("err", task_id, exc_blob, traceback_str, (t0, t1)).
#
# Layout (little-endian):
#   u8 version, u16 nitems
#   item: u8 kind (0 done / 1 err), 16s task_id, d t0, d t1
#     done: u8 nentries; entry: u8 etype
#           etype 0: u32 len, inline blob
#           etype 1: u64 offset, u64 nbytes
#     err:  u32 len, exc blob; u32 len, utf-8 traceback

import struct as _struct

COMPLETION_VERSION = 1
_C_U8 = _struct.Struct("<B")
_C_U16 = _struct.Struct("<H")
_C_U32 = _struct.Struct("<I")
_C_FIX = _struct.Struct("<B16sdd")
_C_SHM = _struct.Struct("<QQ")


def encode_completion_envelope(items) -> "bytes | None":
    """Pack a completion batch; None = an item has a shape the codec
    doesn't know (caller keeps it on the pipe)."""
    parts = [_C_U8.pack(COMPLETION_VERSION), _C_U16.pack(len(items))]
    ap = parts.append
    try:
        for it in items:
            kind = it[0]
            if kind == "done":
                _, tid, entries, (t0, t1) = it
                ap(_C_FIX.pack(0, tid, t0, t1))
                ap(_C_U8.pack(len(entries)))
                for e in entries:
                    if e[0] == "inline":
                        ap(b"\x00")
                        ap(_C_U32.pack(len(e[1])))
                        ap(e[1])
                    elif e[0] == "shm":
                        ap(b"\x01")
                        ap(_C_SHM.pack(e[1], e[2]))
                    else:
                        return None
            elif kind == "err":
                _, tid, blob, tb, (t0, t1) = it
                tbb = tb.encode("utf-8", "replace")
                ap(_C_FIX.pack(1, tid, t0, t1))
                ap(_C_U32.pack(len(blob)))
                ap(blob)
                ap(_C_U32.pack(len(tbb)))
                ap(tbb)
            else:
                return None
    except Exception:
        return None
    return b"".join(parts)


def decode_completion_envelope(data) -> list:
    """Unpack back into the pipe-shaped completion tuples (tags
    restored, so downstream handling is transport-agnostic)."""
    mv = memoryview(data)
    if mv[0] != COMPLETION_VERSION:
        raise ValueError(f"unknown completion-envelope version {mv[0]}")
    n = _C_U16.unpack_from(mv, 1)[0]
    off = 3
    out = []
    for _ in range(n):
        kind, tid, t0, t1 = _C_FIX.unpack_from(mv, off)
        off += 33
        if kind == 0:
            ne = mv[off]
            off += 1
            entries = []
            for _ in range(ne):
                et = mv[off]
                off += 1
                if et == 0:
                    ln = _C_U32.unpack_from(mv, off)[0]
                    off += 4
                    entries.append(("inline", bytes(mv[off:off + ln])))
                    off += ln
                else:
                    o, nb = _C_SHM.unpack_from(mv, off)
                    off += 16
                    entries.append(("shm", o, nb))
            out.append(("done", tid, entries, (t0, t1)))
        else:
            ln = _C_U32.unpack_from(mv, off)[0]
            off += 4
            blob = bytes(mv[off:off + ln])
            off += ln
            ln = _C_U32.unpack_from(mv, off)[0]
            off += 4
            tb = str(mv[off:off + ln], "utf-8")
            off += ln
            out.append(("err", tid, blob, tb, (t0, t1)))
    return out


# the framed serialization of None, precomputed: workers return it for
# no-result tasks by reference and the owner recognizes it by bytes,
# so the dominant fan-out shape never touches a pickler on either side
NONE_FRAMED = serialize(None).to_bytes()
