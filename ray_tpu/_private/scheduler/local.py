"""Event-driven scheduler — the semantics oracle.

Per-event O(1) decisions in the style of the reference's
ClusterTaskManager::QueueAndScheduleTask + LocalTaskManager dispatch
(ray: src/ray/raylet/scheduling/cluster_task_manager.cc,
local_task_manager.cc): tasks wait for dependencies, then for resources,
then dispatch. Node selection uses the hybrid policy analog: prefer the
least-loaded feasible node, preferring node 0 (local) until its load
crosses the configured threshold.

The tensorized scheduler (scheduler/tensor.py) must make decisions
consistent with this one; property tests drive both with the same task
graphs.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.scheduler.base import PendingTask, SchedulerBase
from ray_tpu._private.task_spec import resources_to_vector


class NodeState:
    __slots__ = ("capacity", "available", "node_id")

    def __init__(self, capacity: Tuple[float, ...], node_id=None):
        self.capacity = list(capacity)
        self.available = list(capacity)
        self.node_id = node_id

    def fits(self, demand: Tuple[float, ...]) -> bool:
        return all(a >= d for a, d in zip(self.available, demand))

    def feasible(self, demand: Tuple[float, ...]) -> bool:
        return all(c >= d for c, d in zip(self.capacity, demand))

    def allocate(self, demand: Tuple[float, ...]) -> None:
        for i, d in enumerate(demand):
            self.available[i] -= d

    def release(self, demand: Tuple[float, ...]) -> None:
        for i, d in enumerate(demand):
            self.available[i] = min(self.available[i] + d, self.capacity[i])

    def load(self) -> float:
        """Fraction of the binding resource in use."""
        worst = 0.0
        for c, a in zip(self.capacity, self.available):
            if c > 0:
                worst = max(worst, (c - a) / c)
        return worst


class EventScheduler(SchedulerBase):
    def __init__(self, nodes: List[NodeState],
                 dispatcher: Callable[[PendingTask], None],
                 store_contains: Optional[Callable[[ObjectID], bool]] = None):
        """dispatcher runs the task (typically enqueues to an executor pool);
        it must call notify_task_finished when done. store_contains is
        checked under the scheduler lock so an object becoming ready
        concurrently with submit() cannot be missed."""
        self._nodes = nodes
        self._dispatch = dispatcher
        self._store_contains = store_contains or (lambda oid: False)
        self._lock = threading.Lock()
        # object_id -> tasks waiting on it
        self._waiters: Dict[ObjectID, List[PendingTask]] = {}
        self._dep_count: Dict[TaskID, int] = {}
        self._tasks: Dict[TaskID, PendingTask] = {}
        self._ready: Deque[PendingTask] = collections.deque()
        self._infeasible: List[PendingTask] = []
        self._num_submitted = 0
        self._num_dispatched = 0
        self._num_finished = 0

    # -- SchedulerBase -----------------------------------------------------
    def submit(self, task: PendingTask) -> None:
        to_dispatch = []
        with self._lock:
            self._num_submitted += 1
            self._tasks[task.spec.task_id] = task
            remaining = 0
            for dep in task.deps:
                if self._store_contains(dep):
                    continue
                self._waiters.setdefault(dep, []).append(task)
                remaining += 1
            if remaining == 0:
                self._ready.append(task)
            else:
                self._dep_count[task.spec.task_id] = remaining
            to_dispatch = self._drain_ready_locked()
        self._run_dispatch(to_dispatch)

    def notify_object_ready(self, object_id: ObjectID) -> None:
        to_dispatch = []
        with self._lock:
            for task in self._waiters.pop(object_id, []):
                tid = task.spec.task_id
                if tid not in self._dep_count:
                    continue
                self._dep_count[tid] -= 1
                if self._dep_count[tid] == 0:
                    del self._dep_count[tid]
                    self._ready.append(task)
            to_dispatch = self._drain_ready_locked()
        self._run_dispatch(to_dispatch)

    def notify_task_finished(self, task_id: TaskID, node_index: int,
                             resources: Dict[str, float]) -> None:
        to_dispatch = []
        with self._lock:
            self._num_finished += 1
            self._tasks.pop(task_id, None)
            if 0 <= node_index < len(self._nodes):
                self._nodes[node_index].release(resources_to_vector(resources))
            to_dispatch = self._drain_ready_locked()
        self._run_dispatch(to_dispatch)

    def cancel(self, task_id: TaskID) -> bool:
        with self._lock:
            task = self._tasks.get(task_id)
            if task is None or task.node_index >= 0:
                return False  # unknown or already dispatched
            task.cancelled = True
            self._tasks.pop(task_id, None)
            self._dep_count.pop(task_id, None)
            try:
                self._ready.remove(task)
            except ValueError:
                pass
            return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "submitted": self._num_submitted,
                "dispatched": self._num_dispatched,
                "finished": self._num_finished,
                "waiting_deps": len(self._dep_count),
                "ready_queue": len(self._ready),
                "infeasible": len(self._infeasible),
                "nodes": [
                    {"available": list(n.available), "capacity": list(n.capacity)}
                    for n in self._nodes
                ],
            }

    def shutdown(self) -> None:
        with self._lock:
            self._ready.clear()
            self._waiters.clear()
            self._dep_count.clear()

    # -- node management (used by the virtual cluster test util) -----------
    def add_node(self, node: NodeState) -> int:
        to_dispatch = []
        with self._lock:
            self._nodes.append(node)
            idx = len(self._nodes) - 1
            # a new node can make previously-infeasible tasks feasible;
            # without this rescan they would be parked forever
            if self._infeasible:
                self._ready.extend(self._infeasible)
                self._infeasible.clear()
            to_dispatch = self._drain_ready_locked()
        self._run_dispatch(to_dispatch)
        return idx

    def remove_node(self, node_index: int) -> None:
        with self._lock:
            self._nodes[node_index].capacity = [0.0] * len(
                self._nodes[node_index].capacity)
            self._nodes[node_index].available = [0.0] * len(
                self._nodes[node_index].available)

    # -- internals ---------------------------------------------------------
    def _drain_ready_locked(self) -> List[PendingTask]:
        """Pop ready tasks whose resources fit; assign nodes (hybrid policy)."""
        out = []
        threshold = GLOBAL_CONFIG.sched_hybrid_threshold
        deferred: List[PendingTask] = []
        while self._ready:
            task = self._ready.popleft()
            if task.cancelled:
                continue
            demand = task.spec.resource_vector()
            idx = self._pick_node(demand, threshold)
            if idx is None:
                if not any(n.feasible(demand) for n in self._nodes):
                    self._infeasible.append(task)
                else:
                    deferred.append(task)
                continue
            self._nodes[idx].allocate(demand)
            task.node_index = idx
            self._num_dispatched += 1
            out.append(task)
        self._ready.extend(deferred)
        return out

    def _pick_node(self, demand: Tuple[float, ...],
                   threshold: float) -> Optional[int]:
        # hybrid: local (node 0) until its load crosses threshold, then the
        # least-loaded remote node that fits
        if self._nodes and self._nodes[0].fits(demand) \
                and self._nodes[0].load() < threshold:
            return 0
        best, best_load = None, float("inf")
        for i, n in enumerate(self._nodes):
            if n.fits(demand):
                ld = n.load()
                if ld < best_load:
                    best, best_load = i, ld
        return best

    def _run_dispatch(self, tasks: List[PendingTask]) -> None:
        for task in tasks:
            self._dispatch(task)
