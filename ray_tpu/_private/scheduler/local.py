"""Event-driven scheduler — the semantics oracle.

Per-event O(1) decisions in the style of the reference's
ClusterTaskManager::QueueAndScheduleTask + LocalTaskManager dispatch
(ray: src/ray/raylet/scheduling/cluster_task_manager.cc,
local_task_manager.cc): tasks wait for dependencies, then for resources,
then dispatch. Node selection uses the hybrid policy analog: prefer the
least-loaded feasible node, preferring node 0 (local) until its load
crosses the configured threshold.

The tensorized scheduler (scheduler/tensor.py) must make decisions
consistent with this one; property tests drive both with the same task
graphs.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.scheduler.base import PendingTask, SchedulerBase
from ray_tpu._private.task_spec import custom_resources, resources_to_vector


class NodeState:
    __slots__ = ("capacity", "available", "node_id", "pg_id", "bundle_index",
                 "parent", "defunct", "custom", "custom_avail",
                 "window_factor")

    def __init__(self, capacity: Tuple[float, ...], node_id=None,
                 pg_id=None, bundle_index: int = -1, parent: int = -1,
                 custom_resources: Optional[Dict[str, float]] = None,
                 window_factor: int = 1):
        self.capacity = list(capacity)
        self.available = list(capacity)
        self.node_id = node_id
        # declared NAMED resources: per-name placement feasibility rides
        # the eligibility masks; per-name QUANTITY is debited host-side
        # at allocate/release (the batched kernel sees the aggregate
        # CUSTOM dimension; the per-name check is re-validated at apply)
        self.custom: Dict[str, float] = dict(custom_resources or {})
        self.custom_avail: Dict[str, float] = dict(self.custom)
        # bundle rows: a committed placement-group bundle is a virtual
        # node whose capacity was carved out of ``parent``'s availability
        # (reference: PG bundles become per-bundle resources,
        # ray: src/ray/gcs/gcs_server/gcs_placement_group_scheduler.cc)
        self.pg_id = pg_id              # None = physical node
        self.bundle_index = bundle_index
        self.parent = parent
        # removed PG whose in-flight tasks haven't finished: remaining
        # capacity returns to the parent as each task releases
        self.defunct = False
        # dispatch window (reference: the raylet's local dispatch queue
        # + ReportWorkerBacklog): simple CPU-only tasks may be leased to
        # this node up to window_factor x cpu-capacity OUTSTANDING, the
        # excess queueing at the node's pool; real concurrency stays
        # bounded by the pool's worker processes. 1 = strict (no
        # over-dispatch). Only >1 for process-pool nodes on
        # oversubscribed hosts.
        self.window_factor = window_factor

    @property
    def is_bundle(self) -> bool:
        return self.pg_id is not None

    def has_custom(self, custom: Dict[str, float]) -> bool:
        """Per-name feasibility: every named demand must be declared on
        the node at sufficient capacity."""
        return all(self.custom.get(k, 0.0) >= v for k, v in custom.items())

    def fits_custom(self, custom: Dict[str, float]) -> bool:
        """Per-name availability (has_custom checks declared capacity)."""
        return all(self.custom_avail.get(k, 0.0) >= v - 1e-9
                   for k, v in custom.items())

    def allocate_custom(self, custom: Dict[str, float]) -> None:
        for k, v in custom.items():
            self.custom_avail[k] = self.custom_avail.get(k, 0.0) - v

    def release_custom(self, custom: Dict[str, float]) -> None:
        for k, v in custom.items():
            self.custom_avail[k] = min(
                self.custom_avail.get(k, 0.0) + v, self.custom.get(k, 0.0))

    def fits(self, demand: Tuple[float, ...]) -> bool:
        return all(a >= d for a, d in zip(self.available, demand))

    def feasible(self, demand: Tuple[float, ...]) -> bool:
        return all(c >= d for c, d in zip(self.capacity, demand))

    def allocate(self, demand: Tuple[float, ...]) -> None:
        for i, d in enumerate(demand):
            self.available[i] -= d

    def release(self, demand: Tuple[float, ...]) -> None:
        for i, d in enumerate(demand):
            self.available[i] = min(self.available[i] + d, self.capacity[i])

    def load(self) -> float:
        """Fraction of the binding resource in use."""
        worst = 0.0
        for c, a in zip(self.capacity, self.available):
            if c > 0:
                worst = max(worst, (c - a) / c)
        return worst


class EventScheduler(SchedulerBase):
    def __init__(self, nodes: List[NodeState],
                 dispatcher: Callable[[PendingTask], None],
                 store_contains: Optional[Callable[[ObjectID], bool]] = None):
        """dispatcher runs the task (typically enqueues to an executor pool);
        it must call notify_task_finished when done. store_contains is
        checked under the scheduler lock so an object becoming ready
        concurrently with submit() cannot be missed."""
        self._nodes = nodes
        self._dispatch = dispatcher
        self._store_contains = store_contains or (lambda oid: False)
        self._lock = threading.Lock()
        # object_id -> tasks waiting on it
        self._waiters: Dict[ObjectID, List[PendingTask]] = {}
        self._dep_count: Dict[TaskID, int] = {}
        self._tasks: Dict[TaskID, PendingTask] = {}
        self._ready: Deque[PendingTask] = collections.deque()
        self._infeasible: List[PendingTask] = []
        self._num_submitted = 0
        self._num_dispatched = 0
        self._num_finished = 0
        # per-node leases outstanding (dispatched, not yet finished):
        # the spillback bound for locality preference reads this
        self._outstanding: Dict[int, int] = {}

    # -- SchedulerBase -----------------------------------------------------
    def submit(self, task: PendingTask) -> None:
        to_dispatch = []
        with self._lock:
            self._num_submitted += 1
            self._tasks[task.spec.task_id] = task
            remaining = 0
            for dep in task.deps:
                if self._store_contains(dep):
                    continue
                self._waiters.setdefault(dep, []).append(task)
                remaining += 1
            if remaining == 0:
                self._ready.append(task)
            else:
                self._dep_count[task.spec.task_id] = remaining
            to_dispatch = self._drain_ready_locked()
        self._run_dispatch(to_dispatch)

    def notify_object_ready(self, object_id: ObjectID) -> None:
        to_dispatch = []
        newly_ready = []
        with self._lock:
            for task in self._waiters.pop(object_id, []):
                tid = task.spec.task_id
                if tid not in self._dep_count:
                    continue
                self._dep_count[tid] -= 1
                if self._dep_count[tid] == 0:
                    del self._dep_count[tid]
                    self._ready.append(task)
                    newly_ready.append(tid)
            to_dispatch = self._drain_ready_locked()
        if newly_ready:
            te = self.task_events
            if te is not None:
                te.record_ready_batch(newly_ready)
        self._run_dispatch(to_dispatch)

    def notify_task_finished(self, task_id: TaskID, node_index: int,
                             resources: Dict[str, float]) -> None:
        to_dispatch = []
        with self._lock:
            self._num_finished += 1
            self._tasks.pop(task_id, None)
            if node_index in self._outstanding:
                self._outstanding[node_index] -= 1
                if self._outstanding[node_index] <= 0:
                    del self._outstanding[node_index]
            if 0 <= node_index < len(self._nodes):
                node = self._nodes[node_index]
                vec = resources_to_vector(resources)
                custom = custom_resources(resources)
                if node.defunct:
                    # removed bundle: this task's share of the carved-out
                    # capacity returns to the parent now that it is free
                    self._nodes[node.parent].release(vec)
                    self._nodes[node.parent].release_custom(custom)
                    node.capacity = [max(c - v, 0.0)
                                     for c, v in zip(node.capacity, vec)]
                else:
                    node.release(vec)
                    node.release_custom(custom)
            to_dispatch = self._drain_ready_locked()
        self._run_dispatch(to_dispatch)

    def cancel(self, task_id: TaskID) -> bool:
        with self._lock:
            task = self._tasks.get(task_id)
            if task is None or task.node_index >= 0:
                return False  # unknown or already dispatched
            task.cancelled = True
            self._tasks.pop(task_id, None)
            self._dep_count.pop(task_id, None)
            try:
                self._ready.remove(task)
            except ValueError:
                pass
            return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "submitted": self._num_submitted,
                "dispatched": self._num_dispatched,
                "finished": self._num_finished,
                "local_dispatch": self._num_local_dispatch,
                "spillback": self._num_spillback,
                "waiting_deps": len(self._dep_count),
                "ready_queue": len(self._ready),
                "infeasible": len(self._infeasible),
                "nodes": [
                    {"available": list(n.available),
                     "capacity": list(n.capacity),
                     "is_bundle": n.is_bundle,
                     "custom": dict(n.custom),
                     "custom_avail": dict(n.custom_avail)}
                    for n in self._nodes
                ],
            }

    def shutdown(self) -> None:
        with self._lock:
            self._ready.clear()
            self._waiters.clear()
            self._dep_count.clear()

    def pending_entries(self):
        """(spec, unresolved deps) for every not-yet-dispatched task."""
        with self._lock:
            seen = set()
            out = []
            # cancelled tasks linger in _waiters/_infeasible (cancel()
            # pops the other indexes); a snapshot must not resurrect them
            for bucket in (self._ready, self._infeasible):
                for t in bucket:
                    if not t.cancelled and t.spec.task_id not in seen:
                        seen.add(t.spec.task_id)
                        out.append((t.spec, list(t.deps)))
            for waiters in self._waiters.values():
                for t in waiters:
                    if not t.cancelled and t.spec.task_id not in seen:
                        seen.add(t.spec.task_id)
                        out.append((t.spec, list(t.deps)))
            return out

    def device_state_snapshot(self):
        return {}  # the oracle keeps no array state

    def task_table(self) -> List[Dict[str, Any]]:
        """Live tasks (oracle-scheduler view; mirrors
        TensorScheduler.task_table)."""
        with self._lock:
            rows = []
            ready_ids = {t.spec.task_id for t in self._ready}
            infeasible_ids = {t.spec.task_id for t in self._infeasible}
            for tid, task in self._tasks.items():
                if tid in self._dep_count:
                    state = "PENDING_ARGS"
                elif tid in infeasible_ids:
                    state = "INFEASIBLE"
                elif tid in ready_ids:
                    state = "PENDING_NODE"
                elif task.node_index >= 0:
                    state = "RUNNING"
                else:
                    state = "PENDING_NODE"
                rows.append({
                    "task_id": tid.hex(),
                    "name": task.spec.name,
                    "state": state,
                    "node_index": task.node_index,
                    "attempt": task.spec.attempt_number,
                    "scheduling_class": -1,
                })
            return rows

    def node_state(self, index: int) -> Optional[NodeState]:
        with self._lock:
            return self._nodes[index] if 0 <= index < len(self._nodes) \
                else None

    def try_allocate(self, index: int, resources: Dict[str, float]) -> bool:
        """Directly charge a row if it fits (actor restart-elsewhere:
        the replacement node must account for the actor's resources)."""
        vec = resources_to_vector(resources)
        custom = custom_resources(resources)
        with self._lock:
            if not (0 <= index < len(self._nodes)):
                return False
            n = self._nodes[index]
            if n.fits(vec) and any(c > 0 for c in n.capacity) \
                    and n.has_custom(custom) and n.fits_custom(custom):
                n.allocate(vec)
                n.allocate_custom(custom)
                return True
            return False

    def node_count(self) -> int:
        with self._lock:
            return len(self._nodes)

    # -- node management (used by the virtual cluster test util) -----------
    def add_node(self, node: NodeState, wake: bool = True) -> int:
        """wake=False defers dispatch until the caller finishes wiring
        the node (pool registration) and calls poke() — see
        TensorScheduler.add_node."""
        with self._lock:
            self._nodes.append(node)
            idx = len(self._nodes) - 1
        if wake:
            # a new node can make previously-infeasible tasks feasible
            self.poke()
        return idx

    def poke(self) -> None:
        to_dispatch = []
        with self._lock:
            if self._infeasible:
                self._ready.extend(self._infeasible)
                self._infeasible.clear()
            to_dispatch = self._drain_ready_locked()
        self._run_dispatch(to_dispatch)

    def remove_node(self, node_index: int) -> None:
        with self._lock:
            self._nodes[node_index].capacity = [0.0] * len(
                self._nodes[node_index].capacity)
            self._nodes[node_index].available = [0.0] * len(
                self._nodes[node_index].available)
            # a dead node's named resources leave the cluster with it
            self._nodes[node_index].custom = {}
            self._nodes[node_index].custom_avail = {}

    # -- placement groups ---------------------------------------------------
    def pack_snapshot(self):
        """(avail [N,R], cap [N,R], row indices) over PHYSICAL nodes only —
        the input to the placement-group bin-pack solve."""
        import numpy as np

        with self._lock:
            rows = [i for i, n in enumerate(self._nodes) if not n.is_bundle]
            avail = np.asarray([self._nodes[i].available for i in rows],
                               dtype=np.float32)
            cap = np.asarray([self._nodes[i].capacity for i in rows],
                             dtype=np.float32)
            return avail, cap, rows

    def add_bundle_nodes(self, pg_id, placements) -> Optional[List[int]]:
        """Atomically reserve bundles: placements = [(parent_row,
        demand_vec, custom_dict), ...] in bundle order. All-or-nothing:
        validates every reservation against current availability first
        (2-phase commit of the reference's PrepareBundleResources/
        CommitBundleResources,
        ray: src/ray/raylet/placement_group_resource_manager.cc). Returns
        the new bundle row indices, or None if any reservation no longer
        fits (caller repacks against a fresh snapshot)."""
        to_dispatch: List[PendingTask] = []
        with self._lock:
            need: Dict[int, List[float]] = {}
            for parent, vec, _custom in placements:
                acc = need.setdefault(parent, [0.0] * len(vec))
                for i, v in enumerate(vec):
                    acc[i] += v
            for parent, total in need.items():
                if not self._nodes[parent].fits(tuple(total)):
                    return None
            rows = []
            for bindex, (parent, vec, custom) in enumerate(placements):
                self._nodes[parent].allocate(tuple(vec))
                self._nodes[parent].allocate_custom(custom)
                self._nodes.append(NodeState(
                    tuple(vec), node_id=self._nodes[parent].node_id,
                    pg_id=pg_id, bundle_index=bindex, parent=parent,
                    custom_resources=custom))
                rows.append(len(self._nodes) - 1)
            # bundle rows make parked PG tasks feasible
            if self._infeasible:
                self._ready.extend(self._infeasible)
                self._infeasible.clear()
            to_dispatch = self._drain_ready_locked()
        self._run_dispatch(to_dispatch)
        return rows

    def drain_pg_tasks(self, pg_id) -> List[PendingTask]:
        """Remove and return every not-yet-dispatched task targeting the
        group (its rows are gone; leaving them queued would hang their
        callers forever)."""
        pid = pg_id.binary()

        def match(t: PendingTask) -> bool:
            p = t.spec.placement_group_id
            return p is not None and p.binary() == pid

        out: List[PendingTask] = []
        with self._lock:
            for bucket in (self._ready, self._infeasible):
                kept = [t for t in bucket if not match(t)]
                out.extend(t for t in bucket if match(t))
                bucket.clear()
                bucket.extend(kept)
            for oid, waiters in list(self._waiters.items()):
                kept = [t for t in waiters if not match(t)]
                out.extend(t for t in waiters if match(t))
                if kept:
                    self._waiters[oid] = kept
                else:
                    del self._waiters[oid]
            seen = set()
            uniq = []
            for t in out:
                tid = t.spec.task_id
                if tid in seen:
                    continue
                seen.add(tid)
                uniq.append(t)
                self._tasks.pop(tid, None)
                self._dep_count.pop(tid, None)
        return uniq

    def remove_pg(self, pg_id) -> None:
        """Release a placement group's bundle rows back to their parents.

        Only the FREE part of each bundle returns immediately; capacity
        held by still-running tasks stays charged against the bundle (the
        row goes ``defunct``) and flows back to the parent task-by-task in
        notify_task_finished — releasing it all at once would overcommit
        the parent while those tasks still occupy it. Rows are zeroed, not
        deleted: indices held by in-flight tasks stay valid."""
        with self._lock:
            for n in self._nodes:
                if n.pg_id == pg_id and not n.defunct \
                        and any(c > 0 for c in n.capacity):
                    parent = self._nodes[n.parent]
                    parent.release(tuple(n.available))
                    # unused named resources return now; the in-use part
                    # follows task-by-task via the defunct completion path
                    parent.release_custom(n.custom_avail)
                    in_use = [c - a for c, a in zip(n.capacity, n.available)]
                    n.capacity = in_use
                    n.available = [0.0] * len(n.available)
                    n.defunct = True

    # -- internals ---------------------------------------------------------
    def _drain_ready_locked(self) -> List[PendingTask]:
        """Pop ready tasks whose resources fit; assign nodes (hybrid policy)."""
        out = []
        threshold = GLOBAL_CONFIG.sched_hybrid_threshold
        locality_on = (GLOBAL_CONFIG.scheduler_locality
                       and self.locations_of is not None)
        spill_depth = GLOBAL_CONFIG.locality_spillback_queue_depth
        plane = self.qos_plane
        if plane is not None and len(self._ready) > 1:
            # QoS drain order: strict tiers first, weighted fair-share
            # between tenants inside a tier, FIFO within a tenant
            tasks = list(self._ready)
            order = plane.order([(t.spec.priority, t.spec.tenant)
                                 for t in tasks])
            self._ready = collections.deque(tasks[i] for i in order)
        deferred: List[PendingTask] = []
        while self._ready:
            task = self._ready.popleft()
            if task.cancelled:
                continue
            demand = task.spec.resource_vector()
            custom = custom_resources(task.spec.resources)
            # resolve soft affinity ONCE: the fallback placement must be
            # used for the infeasibility check too, or a soft-aff task
            # whose fallback nodes are momentarily full parks forever
            placement = self._effective_placement_locked(
                task.spec.placement(), custom)
            # locality: the node holding the most resident input bytes
            # is preferred when feasible; SPREAD / PG / affinity
            # placements keep their own policies untouched
            prefer = None
            if locality_on and placement[0] == "default" \
                    and getattr(task.spec, "arg_sizes", None):
                prefer = self._preferred_node_locked(task.spec.arg_sizes)
            idx = self._pick_node(demand, threshold, placement, custom,
                                  prefer=prefer, spill_depth=spill_depth)
            if idx is None:
                if not any(self._eligible(i, placement, custom)
                           and n.feasible(demand)
                           for i, n in enumerate(self._nodes)):
                    self._infeasible.append(task)
                else:
                    deferred.append(task)
                continue
            self._nodes[idx].allocate(demand)
            self._nodes[idx].allocate_custom(custom)
            task.node_index = idx
            self._num_dispatched += 1
            self._outstanding[idx] = self._outstanding.get(idx, 0) + 1
            out.append(task)
        self._ready.extend(deferred)
        return out

    def _preferred_node_locked(self, arg_sizes) -> Optional[int]:
        """Node row holding the most resident bytes of this task's args
        (primary or staged secondary copies both count; a copy of
        unknown size weighs 1 byte so it still attracts). Ties break to
        the lowest row for determinism."""
        locs_of = self.locations_of
        bytes_on: Dict[int, int] = {}
        for oid, nbytes in arg_sizes:
            for node in locs_of(oid):
                bytes_on[node] = bytes_on.get(node, 0) + max(int(nbytes), 1)
        if not bytes_on:
            return None
        return max(bytes_on.items(), key=lambda kv: (kv[1], -kv[0]))[0]

    def _effective_placement_locked(self, placement: Tuple,
                                    custom: Dict[str, float]) -> Tuple:
        """Soft node affinity whose target is missing/dead resolves to the
        default placement (mirrors TensorScheduler._mask_row)."""
        if placement[0] == "aff" and len(placement) > 2 and placement[2]:
            target_alive = any(
                self._eligible(i, placement, custom)
                and any(c > 0 for c in n.capacity)
                for i, n in enumerate(self._nodes))
            if not target_alive:
                return ("default",)
        return placement

    def _eligible(self, idx: int, placement: Tuple,
                  custom: Dict[str, float] = {}) -> bool:
        node = self._nodes[idx]
        if custom and not node.has_custom(custom):
            return False
        kind = placement[0]
        if kind == "pg":
            _, pid, bindex = placement
            return (node.pg_id is not None
                    and not node.defunct
                    and node.pg_id.binary() == pid
                    and (bindex < 0 or node.bundle_index == bindex))
        if kind == "aff":
            nid = placement[1]
            node_id = node.node_id
            node_id = node_id.binary() if hasattr(node_id, "binary") \
                else node_id
            return not node.is_bundle and node_id == nid
        return not node.is_bundle   # default / spread

    def _pick_node(self, demand: Tuple[float, ...], threshold: float,
                   placement: Tuple = ("default",),
                   custom: Dict[str, float] = {},
                   prefer: Optional[int] = None,
                   spill_depth: int = 0) -> Optional[int]:
        kind = placement[0]
        if kind == "aff":
            best, best_load = None, float("inf")
            target_alive = False
            for i, n in enumerate(self._nodes):
                if self._eligible(i, placement, custom):
                    if any(c > 0 for c in n.capacity):
                        target_alive = True
                    if n.fits(demand) and n.fits_custom(custom):
                        ld = n.load()
                        if ld < best_load:
                            best, best_load = i, ld
            if best is not None:
                return best
            if target_alive:
                return None  # node exists but is busy: wait for it
            # soft affinity falls back to the default policy only when the
            # target node is missing or dead (documented semantics)
            if len(placement) > 2 and placement[2]:
                placement = ("default",)
            else:
                return None
            kind = "default"
        # locality preference outranks the hybrid local bias: the node
        # holding the task's input bytes takes it when it fits; when it is
        # momentarily full the task WAITS for it, but only while its
        # outstanding-lease depth stays under the spillback bound —
        # beyond that the task falls through to the normal policy
        if kind == "default" and prefer is not None \
                and 0 <= prefer < len(self._nodes):
            n = self._nodes[prefer]
            if self._eligible(prefer, placement, custom) \
                    and n.feasible(demand) and n.has_custom(custom):
                if n.fits(demand) and n.fits_custom(custom):
                    return prefer
                if self._outstanding.get(prefer, 0) < spill_depth:
                    return None  # bounded wait for the data-resident node
        # hybrid: local (node 0) until its load crosses threshold, then the
        # least-loaded eligible node that fits. SPREAD and PG classes skip
        # the local bias (PG rows exclude node 0 anyway).
        if kind == "default" and self._nodes \
                and self._eligible(0, placement, custom) \
                and self._nodes[0].fits(demand) \
                and self._nodes[0].fits_custom(custom) \
                and self._nodes[0].load() < threshold:
            return 0
        best, best_load = None, float("inf")
        for i, n in enumerate(self._nodes):
            if self._eligible(i, placement, custom) and n.fits(demand) \
                    and n.fits_custom(custom):
                ld = n.load()
                if ld < best_load:
                    best, best_load = i, ld
        return best

    def _run_dispatch(self, tasks: List[PendingTask]) -> None:
        for task in tasks:
            self._dispatch(task)
