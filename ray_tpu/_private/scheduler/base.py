"""Scheduler interface shared by the event-driven oracle and the
tensorized device-resident implementation."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.task_spec import TaskSpec


@dataclasses.dataclass
class PendingTask:
    spec: TaskSpec
    deps: List[ObjectID]           # unresolved top-level ObjectRef args
    execute: Callable[["PendingTask", int], None]  # (task, node_index) -> None
    # filled by the scheduler:
    node_index: int = -1
    cancelled: bool = False


class SchedulerBase:
    """Submission boundary. Implementations must be thread-safe."""

    # Optional TaskEventAggregator the worker attaches after
    # construction; implementations call record_ready_batch() when a
    # dep-blocked task's last dependency lands (no-dep tasks skip the
    # hook entirely: READY defaults to SUBMITTED at read time).
    task_events = None

    # Optional object-location provider the worker attaches after
    # construction: locations_of(object_id) -> List[int] of node rows
    # holding a copy (primary first). Drives the locality scoring
    # column; None (or an empty list per oid) disables it.
    locations_of = None

    # Two-level scheduling counters: node-local admissions this
    # scheduler never placed, and their upward spillbacks that landed
    # back on its queue. Bare class attrs so both implementations (and
    # tests' stubs) inherit the zero without extra __init__ plumbing;
    # the notes below rebind instance attrs, and the only writers are
    # the head's daemon-demux/rpc threads, which bump under the GIL
    # at report granularity (exactness is not load-bearing — the
    # authoritative counts live in worker.two_level_stats).
    _num_local_dispatch = 0
    _num_spillback = 0

    # Optional QosPlane the worker attaches after construction when the
    # qos knob is on: drains consult plane.order() so ready work
    # dispatches strict-tier-first with weighted fair-share between
    # tenants inside a tier. None (the class default) keeps the FIFO
    # drain order byte-for-byte pre-QoS.
    qos_plane = None

    def note_local_dispatch(self) -> None:
        """A node's LocalScheduler admitted a worker-submitted task
        without this (head) scheduler ever seeing it."""
        self._num_local_dispatch += 1

    def note_spillback(self) -> None:
        """A node declined a local submission (queue full / unfit) and
        spilled it up to this scheduler's normal path."""
        self._num_spillback += 1

    def submit(self, task: PendingTask) -> None:
        raise NotImplementedError

    def submit_many(self, tasks: List[PendingTask]) -> None:
        """Batch submission: implementations override to take their
        queue lock and wake the tick loop ONCE per batch."""
        for t in tasks:
            self.submit(t)

    def node_state(self, index: int):
        """NodeState at a row (locked read). None if out of range."""
        raise NotImplementedError

    def node_count(self) -> int:
        raise NotImplementedError

    def notify_object_ready(self, object_id: ObjectID) -> None:
        """An object a pending task depends on became available."""
        raise NotImplementedError

    def notify_task_finished(self, task_id: TaskID, node_index: int,
                             resources: Dict[str, float]) -> None:
        """Resources released on the node that ran the task."""
        raise NotImplementedError

    def notify_batch(self, ready_objects: List[ObjectID],
                     finished: List[tuple]) -> None:
        """Deliver many object-ready + task-finished events with one
        wakeup (completion batching on the hot path; ``finished`` rows
        are (task_id, node_index, resources) tuples). Default: loop."""
        for oid in ready_objects:
            self.notify_object_ready(oid)
        for task_id, node_index, resources in finished:
            self.notify_task_finished(task_id, node_index, resources)

    def cancel(self, task_id: TaskID) -> bool:
        """Remove a queued task. Returns True if it had not started."""
        raise NotImplementedError

    def stats(self) -> Dict[str, Any]:
        raise NotImplementedError

    def shutdown(self) -> None:
        raise NotImplementedError
