"""Batched scheduling kernels — the device-resident decision core.

This replaces the reference's per-task C++ event-loop decisions
(ray: src/ray/raylet/scheduling/cluster_task_manager.cc
ClusterTaskManager::ScheduleAndDispatchTasks + local_task_manager.cc
LocalTaskManager dispatch + scheduling_policy.cc HybridSchedulingPolicy)
with data-parallel passes over the whole pending set per tick:

  1. ready-set:   ready = waiting & (indegree == 0)
  2. assignment:  for each scheduling class (the reference's
                  SchedulingClass — tasks with identical (fn, demand)
                  that can share worker leases), partition the ready
                  tasks over nodes by a vectorized capacity fill:
                  per-node fit counts -> cumsum -> searchsorted.
                  The hybrid policy analog: node 0 ("local") is filled
                  first up to the configured load threshold, then all
                  nodes least-loaded-first.
  3. completion wave: fire CSR edges of newly-done producers and
                  decrement consumer indegrees with one segment-add.

Two interchangeable backends with identical semantics:
  - numpy: low-latency host ticks for small/interactive batches
  - jax:   jit-compiled ticks for large batches and the benchmark
           graphs (runs on the TPU; all O(T+E) ops vectorize onto the
           VPU and the partition math is a handful of tiny reductions)

Array-state conventions shared by both backends and TensorScheduler:
  state   int8  [C]   0=FREE 1=WAITING 3=RUNNING 4=DONE  (2 reserved)
  indeg   int32 [C]   outstanding dependency count
  cls     int32 [C]   scheduling-class index into demands
  demands f32  [K,R]  per-class resource demand vectors
  avail   f32  [N,R]  per-node available resources
  cap     f32  [N,R]  per-node capacities
  node_of int32 [C]   assigned node (-1 = unassigned)
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

FREE, WAITING, RUNNING, DONE = 0, 1, 3, 4


# ======================================================================
# numpy backend
# ======================================================================

def assign_np(ready_idx: np.ndarray, cls: np.ndarray, demands: np.ndarray,
              avail: np.ndarray, cap: np.ndarray,
              threshold: float,
              class_mask: Optional[np.ndarray] = None,
              class_spread: Optional[np.ndarray] = None,
              locality: Optional[np.ndarray] = None,
              outstanding: Optional[np.ndarray] = None,
              spill_depth: int = 0
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Assign ready tasks (by arena index) to nodes.

    class_mask [K,N] bool restricts each scheduling class to a node
    subset (placement groups pin classes to their reserved bundle rows;
    normal classes exclude bundle rows; node-affinity pins to one row).
    class_spread [K] bool disables the hybrid local-node bias for
    SPREAD-strategy classes. None = no restriction / no spread.

    locality [len(ready_idx),N] float scores each ready task's
    candidate nodes by resident-arg-bytes (0 = no input data there).
    A task with any nonzero row prefers its argmax node when feasible;
    if that node is momentarily full it WAITS for it — but only while
    the node has fewer than ``spill_depth`` leases outstanding
    (``outstanding`` [N] int), beyond which the task spills back to
    the normal least-loaded fill so a hot node never serializes the
    cluster. SPREAD classes and placement masks override locality.
    None = pre-locality behavior, byte-for-byte.

    Returns (node_of_ready [len(ready_idx)] int32 with -1 for
    not-assigned-this-tick, updated avail). Mutates nothing.
    """
    avail = avail.copy()
    n_nodes = avail.shape[0]
    out = np.full(len(ready_idx), -1, dtype=np.int32)
    if len(ready_idx) == 0:
        return out, avail

    # a removed node zeroes its capacity; it must never receive tasks —
    # without this, zero-demand tasks see it as the least-loaded node
    alive = cap.any(axis=1)
    ready_cls = cls[ready_idx]
    for c in np.unique(ready_cls):
        members = np.flatnonzero(ready_cls == c)  # positions in ready_idx
        d = demands[c]
        elig = alive if class_mask is None else (alive & class_mask[c])
        spread = bool(class_spread[c]) if class_spread is not None else False
        active = d > 0

        # locality pre-pass: tasks with resident input bytes go to (or
        # wait for) the eligible node holding the most of them; the
        # remainder flows through the normal hybrid fill below
        if locality is not None and not spread:
            loc_rows = np.where(elig[None, :], locality[members], 0.0)
            cand = np.flatnonzero(loc_rows.max(axis=1) > 0.0)
            if len(cand):
                handled = np.zeros(len(members), dtype=bool)
                if active.any():
                    cap_ok_l = (cap[:, active] >= d[active]).all(axis=1)
                else:
                    cap_ok_l = np.ones(n_nodes, dtype=bool)
                pend = (outstanding.astype(np.int64).copy()
                        if outstanding is not None
                        else np.zeros(n_nodes, dtype=np.int64))
                for j in cand:
                    pref = int(np.argmax(loc_rows[j]))
                    if not cap_ok_l[pref]:
                        continue  # never feasible there: spill now
                    fits_now = (not active.any()) or bool(
                        (avail[pref, active] >= d[active]).all())
                    if fits_now:
                        out[members[j]] = pref
                        avail[pref] -= d
                        pend[pref] += 1
                        handled[j] = True
                    elif pend[pref] < spill_depth:
                        # bounded wait: stay unassigned this tick
                        # rather than pay the transfer elsewhere
                        handled[j] = True
                members = members[~handled]
                if len(members) == 0:
                    continue
        if active.any():
            with np.errstate(divide="ignore", invalid="ignore"):
                per_r = np.floor(avail[:, active] / d[active])
            fit = np.maximum(per_r.min(axis=1), 0.0)
            fit = np.where(np.isfinite(fit), fit, len(members))
            # infeasible-anywhere guard: nodes whose *capacity* can't ever
            # hold the demand contribute 0 (matches EventScheduler feasible())
            cap_ok = (cap[:, active] >= d[active]).all(axis=1)
            fit = np.where(cap_ok, fit, 0.0)
            # clip to the batch size: unbounded resources (e.g. 1e18 memory
            # capacity) would otherwise make np.repeat materialize petabytes
            fit = np.minimum(fit, len(members)).astype(np.int64)
        else:
            fit = np.full(n_nodes, len(members), dtype=np.int64)
        fit = np.where(elig, fit, 0)

        # hybrid policy: node 0 takes tasks while its load stays under the
        # threshold, then every node least-loaded-first up to its fit count.
        used = cap - avail
        with np.errstate(divide="ignore", invalid="ignore"):
            load = np.where(cap > 0, used / np.maximum(cap, 1e-9), 0.0).max(axis=1)
        if spread:
            t0 = 0
        elif active.any() and fit[0] > 0 and load[0] < threshold:
            room = np.floor((threshold * cap[0, active] - used[0, active])
                            / d[active]).min()
            t0 = int(np.clip(room, 0, fit[0]))
        elif not active.any():
            t0 = len(members) if load[0] < threshold and elig[0] else 0
        else:
            t0 = 0
        order = np.argsort(load, kind="stable")
        if spread:
            # round-robin over eligible nodes (least-loaded first): one
            # task per node per round, so members actually spread instead
            # of filling the emptiest node to its fit count
            counts_o = fit[order].astype(np.int64)
            max_r = int(counts_o.max(initial=0))
            if max_r:
                rounds = counts_o[None, :] > np.arange(max_r)[:, None]
                assignment_nodes = order.astype(np.int32)[
                    np.nonzero(rounds)[1]]
            else:
                assignment_nodes = np.zeros(0, dtype=np.int32)
        else:
            counts = [min(t0, len(members))]
            nodes_seq = [0]
            remaining_fit = fit.copy()
            remaining_fit[0] -= counts[0]
            for i in order:
                nodes_seq.append(int(i))
                counts.append(int(remaining_fit[i]))
            assignment_nodes = np.repeat(np.asarray(nodes_seq, dtype=np.int32),
                                         np.asarray(counts, dtype=np.int64))
        take = min(len(members), len(assignment_nodes))
        if take > 0:
            chosen = assignment_nodes[:take]
            out[members[:take]] = chosen
            # ufunc.at accumulates correctly over repeated node indices
            np.subtract.at(avail, chosen, d)
    return out, avail


def fire_edges_np(done_mask: np.ndarray, src: np.ndarray, dst: np.ndarray,
                  consumed: np.ndarray, indeg: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Completion wave over a static edge list (bench / bulk-admission path).

    Returns (new indeg, new consumed)."""
    fire = done_mask[src] & ~consumed
    if fire.any():
        indeg = indeg.copy()
        np.subtract.at(indeg, dst[fire], 1)
        consumed = consumed | fire
    return indeg, consumed


def pack_bundles_np(demands: np.ndarray, avail: np.ndarray, cap: np.ndarray,
                    strategy: str,
                    eligible: Optional[np.ndarray] = None
                    ) -> Optional[np.ndarray]:
    """Bin-pack one placement group's bundles onto nodes.

    The decision core of the reference's GcsPlacementGroupScheduler
    (ray: src/ray/gcs/gcs_server/gcs_placement_group_scheduler.cc) as a
    vectorized solve: demands [B,R], avail/cap [N,R]. ``eligible`` [B,N]
    restricts which nodes may host each bundle (per-NAME custom-resource
    feasibility computed by the caller). Returns node index per bundle,
    or None if no placement exists under ``avail``.

    Strategies (reference: python/ray/util/placement_group.py):
      PACK         prefer one node, spill when full
      SPREAD       prefer distinct nodes, reuse when fewer nodes
      STRICT_PACK  all bundles on ONE node or fail
      STRICT_SPREAD all bundles on DISTINCT nodes or fail
    """
    B, R = demands.shape
    N = avail.shape[0]
    alive = cap.any(axis=1)
    ok = np.broadcast_to(alive, (B, N)).copy()
    if eligible is not None:
        ok &= eligible
    rem = avail.copy()
    out = np.full(B, -1, dtype=np.int32)
    # least-loaded-first node order (deterministic tiebreak by index)
    with np.errstate(divide="ignore", invalid="ignore"):
        load = np.where(cap > 0, (cap - avail) / np.maximum(cap, 1e-9),
                        0.0).max(axis=1)
    order = np.argsort(load, kind="stable")

    if strategy == "STRICT_PACK":
        total = demands.sum(axis=0)
        all_ok = ok.all(axis=0)
        for n in order:
            if all_ok[n] and (rem[n] >= total).all():
                out[:] = n
                return out
        return None

    # big bundles first: greedy first-fit-decreasing
    bundle_order = np.argsort(-demands.sum(axis=1), kind="stable")
    if strategy == "STRICT_SPREAD":
        used = np.zeros(N, dtype=bool)
        for b in bundle_order:
            placed = False
            for n in order:
                if ok[b, n] and not used[n] \
                        and (rem[n] >= demands[b]).all():
                    out[b] = n
                    rem[n] -= demands[b]
                    used[n] = True
                    placed = True
                    break
            if not placed:
                return None
        return out

    if strategy == "SPREAD":
        used = np.zeros(N, dtype=bool)
        for b in bundle_order:
            placed = False
            for prefer_fresh in (True, False):
                for n in order:
                    if not ok[b, n] or (used[n] and prefer_fresh):
                        continue
                    if (rem[n] >= demands[b]).all():
                        out[b] = n
                        rem[n] -= demands[b]
                        used[n] = True
                        placed = True
                        break
                if placed:
                    break
            if not placed:
                return None
        return out

    # PACK (default): fill the least-loaded node, spill in node order
    for b in bundle_order:
        placed = False
        for n in order:
            if ok[b, n] and (rem[n] >= demands[b]).all():
                out[b] = n
                rem[n] -= demands[b]
                placed = True
                break
        if not placed:
            return None
    return out


def jax_pack_many(demands, avail, cap, *, strict_spread: bool):
    """Batched PG bin-pack on device: G placement groups of B bundles
    each ([G,B,R]) packed against ONE shared node state [N,R] — the
    north star's \"GCS placement-group packing ... batched bin-packing
    solve co-resident on the same chip\". Sequential consumption over
    (group, bundle) via nested scans; returns (node_of [G,B], ok [G],
    final avail). First-fit over least-index nodes (deterministic).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    G, B, R = demands.shape
    N = avail.shape[0]

    @jax.jit
    def pack(demands, avail, cap):
        alive = (cap > 0).any(axis=1)

        def per_group(carry, g):
            avail = carry

            def per_bundle(bcarry, b):
                rem, used, node_of, ok = bcarry
                d = demands[g, b]
                fits = (rem >= d[None, :]).all(axis=1) & alive
                if strict_spread:
                    fits = fits & ~used
                n = jnp.argmax(fits)  # first fitting node
                found = fits.any()
                rem = jnp.where(found,
                                rem.at[n].add(-d), rem)
                used = used.at[n].set(used[n] | found)
                node_of = node_of.at[b].set(jnp.where(found, n, -1))
                return (rem, used, node_of, ok & found), None

            (rem, _used, node_of, ok), _ = lax.scan(
                per_bundle,
                (avail, jnp.zeros(N, bool),
                 jnp.full(B, -1, jnp.int32), jnp.bool_(True)),
                jnp.arange(B))
            # 2-phase: commit the group's reservation only if every
            # bundle found a node (prepare-all-or-rollback)
            avail = jnp.where(ok, rem, avail)
            node_of = jnp.where(ok, node_of, jnp.full(B, -1, jnp.int32))
            return avail, (node_of, ok)

        avail, (node_of, ok) = lax.scan(per_group, avail, jnp.arange(G))
        return node_of, ok, avail

    return pack(demands, avail, cap)


def pack_gangs_tiered_np(demands: np.ndarray, tiers: np.ndarray,
                         avail: np.ndarray, cap: np.ndarray,
                         spread: Optional[np.ndarray] = None
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Tier-aware batched gang pack (QoS plane / gang-aware autoscaler).

    Like the sequential core of :func:`jax_pack_many` but gangs are
    admitted in strict priority-tier order — higher ``tiers[g]`` first,
    FIFO (submission index) within a tier — so a low-tier gang can never
    reserve capacity ahead of a higher-tier one that also fits.  Each
    gang of B bundles (demands [G,B,R]) is all-or-nothing against the
    shared node state [N,R]: the reservation commits only if every
    bundle found a node, otherwise the gang's trial consumption rolls
    back entirely (no partial placement is ever visible).

    ``spread`` [G] bool marks gangs whose non-empty bundles must land
    on DISTINCT nodes (STRICT_SPREAD); zero-demand padding rows are
    exempt so callers may pad ragged gang sizes freely.

    Returns (node_of [G,B] with -1 for unplaced, ok [G], final avail)
    in the ORIGINAL gang order regardless of tier permutation.
    """
    G, B, R = demands.shape
    N = avail.shape[0]
    alive = cap.any(axis=1)
    rem = avail.copy()
    node_of = np.full((G, B), -1, dtype=np.int32)
    ok = np.zeros(G, dtype=bool)
    # strict tiers with FIFO inside: stable sort on descending tier
    order = np.argsort(-np.asarray(tiers, dtype=np.int64), kind="stable")
    for g in order:
        trial = rem.copy()
        placed = np.full(B, -1, dtype=np.int32)
        used = np.zeros(N, dtype=bool)
        distinct = bool(spread[g]) if spread is not None else False
        good = True
        for b in range(B):
            d = demands[g, b]
            real = bool((d > 0).any())
            fits = alive & (trial >= d[None, :]).all(axis=1)
            if distinct and real:
                fits &= ~used
            n = int(np.argmax(fits))
            if not fits.any():
                good = False
                break
            trial[n] -= d
            if real:
                used[n] = True
            placed[b] = n
        if good:
            rem = trial
            node_of[g] = placed
            ok[g] = True
    return node_of, ok, rem


def jax_pack_many_tiered(demands, tiers, avail, cap, *,
                         strict_spread: bool):
    """Tier-aware :func:`jax_pack_many`: permute the gang axis into
    strict-tier order (higher first, FIFO within — stable argsort on
    the host, same discipline as :func:`pack_gangs_tiered_np`), run the
    batched on-device pack, then un-permute so callers see results in
    submission order. The scan itself is tier-oblivious; ordering IS
    the policy, exactly like priority drains in the tensor scheduler.
    """
    import numpy as _np

    order = _np.argsort(-_np.asarray(tiers, dtype=_np.int64),
                        kind="stable")
    inv = _np.empty_like(order)
    inv[order] = _np.arange(order.shape[0])
    node_of, ok, avail = jax_pack_many(
        _np.asarray(demands)[order], avail, cap,
        strict_spread=strict_spread)
    return _np.asarray(node_of)[inv], _np.asarray(ok)[inv], avail


# ======================================================================
# jax backend
# ======================================================================

def _assign_class_traced(members, d, avail, cap, threshold, n_nodes, batch_cap,
                         elig=None, spread=None):
    """One scheduling class: partition `members` (bool mask over a flat task
    axis) across nodes. Traced under jit; shared by the runtime assign kernel
    and the benchmark whole-graph tick. Returns (assign_mask, chosen, avail).

    elig [N] bool restricts the class to a node subset (None = all);
    spread (scalar bool) drops the local-node bias (t0 = 0) — the jitted
    approximation of SPREAD (the numpy path does true round-robin).
    """
    import jax
    import jax.numpy as jnp

    rank = jnp.cumsum(members) - 1
    active = d > 0
    safe_d = jnp.where(active, d, 1.0)
    per_r = jnp.where(active[None, :], jnp.floor(avail / safe_d), jnp.inf)
    fit = jnp.clip(per_r.min(axis=1), 0, None)
    cap_ok = jnp.where(active[None, :], cap >= d, True).all(axis=1)
    # dead (removed) nodes have all-zero capacity and must take nothing —
    # even zero-demand tasks, which would otherwise see load 0
    alive = (cap > 0).any(axis=1)
    if elig is not None:
        alive = alive & elig
    fit = jnp.where(cap_ok & alive, fit, 0.0)
    fit = jnp.minimum(fit, jnp.float32(batch_cap)).astype(jnp.int32)

    used_now = cap - avail
    load_now = jnp.where(cap > 0, used_now / jnp.maximum(cap, 1e-9),
                         0.0).max(axis=1)
    k = members.sum()
    room0 = jnp.where(active,
                      jnp.floor((threshold * cap[0] - used_now[0]) / safe_d),
                      jnp.inf).min()
    any_active = active.any()
    t0 = jnp.where(any_active,
                   jnp.clip(room0, 0, fit[0]),
                   jnp.where(load_now[0] < threshold, k, 0))
    t0 = jnp.where((fit[0] > 0) | (~any_active), t0, 0)
    t0 = jnp.where(load_now[0] < threshold, t0, 0)
    t0 = jnp.where(alive[0], t0, 0).astype(jnp.int32)
    if spread is not None:
        t0 = jnp.where(spread, 0, t0)

    order = jnp.argsort(load_now, stable=True)
    fit_rest = fit.at[0].add(-t0)
    seq_nodes = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                 order.astype(jnp.int32)])
    seq_counts = jnp.concatenate([t0[None], fit_rest[order]])
    if spread is not None:
        # True round-robin parity with the numpy path under SPREAD:
        # water-fill the load-ordered nodes — every node takes
        # min(fit, t) with t the number of full round-robin rounds, and
        # the first r nodes still holding capacity take one extra
        # (r = tasks left in the final partial round). Per-node COUNTS
        # match the numpy round-robin exactly; only the task->node
        # interleaving differs (tasks of one class are interchangeable).
        fit_o = fit[order]
        k_tasks = jnp.minimum(k.astype(jnp.int32), fit_o.sum())
        lo = jnp.int32(0)
        hi = jnp.int32(batch_cap)
        for _ in range(int(batch_cap).bit_length() + 1):
            mid = (lo + hi + 1) // 2
            ok_mid = jnp.minimum(fit_o, mid).sum() <= k_tasks
            lo = jnp.where(ok_mid, mid, lo)
            hi = jnp.where(ok_mid, hi, mid - 1)
        base = jnp.minimum(fit_o, lo)
        rem = k_tasks - base.sum()
        can_more = fit_o > lo
        extra = can_more & (jnp.cumsum(can_more) <= rem)
        rr_counts = base + extra.astype(jnp.int32)
        seq_counts = jnp.where(
            spread,
            jnp.concatenate([jnp.zeros((1,), jnp.int32), rr_counts]),
            seq_counts)
    cum = jnp.cumsum(seq_counts)
    total = cum[-1]
    # Segment lookup without any [C, N] materialization: ``rank`` is
    # monotone (a cumsum), so instead of comparing every rank against
    # every boundary (compare-all: ~5 ms at C=1M) or per-element binary
    # search (jnp.searchsorted scan lowering: ~50 ms at C=1M), find each
    # boundary's position in rank (N+1 tiny binary searches), scatter unit
    # deltas, and cumsum: seg[i] = #{j : pos[j] <= i} = #{j : cum[j] <=
    # rank[i]}.
    C = members.shape[0]
    pos = jnp.searchsorted(rank, cum, side="left", method="scan")
    delta = jnp.zeros((C + 1,), jnp.int32).at[jnp.clip(pos, 0, C)].add(1)
    seg = jnp.cumsum(delta)[:C]
    seg = jnp.clip(seg, 0, n_nodes)
    chosen = seq_nodes[seg]
    assign_mask = members & (rank < total) & (rank >= 0)
    # per-node assignment counts from the same boundaries (no one-hot):
    # segment j received min(cum[j], k) - min(cum[j-1], k) tasks
    k = jnp.minimum(rank[-1] + 1, total).astype(cum.dtype)
    m = jnp.minimum(cum, k)
    seg_assigned = (m - jnp.concatenate([jnp.zeros((1,), m.dtype), m[:-1]])
                    ).astype(jnp.float32)
    per_node = jnp.zeros((n_nodes,), jnp.float32).at[seq_nodes].add(
        seg_assigned)
    avail = avail - per_node[:, None] * d[None, :]
    return assign_mask, chosen, avail, per_node


def _scan_classes(ready, cls, demands, avail, cap, threshold, n_nodes,
                  batch_cap, class_mask=None, class_spread=None):
    """Sequential capacity consumption over the class axis via lax.scan.

    Class count is DATA (the demands array's leading dim), not a Python
    unroll: one compiled program serves any class count with the same
    padded shape, so newly observed scheduling classes never trigger an
    XLA recompile (classes are padded to power-of-two buckets by callers;
    a zero-demand padding class has no members and assigns nothing).

    Returns (node_of [C] int32 with -1 = unassigned, assigned [C] bool,
    new avail, release [N,R] = total resources the assigned tasks took,
    for the instant-completion path to hand back).

    ``assigned`` is returned SEPARATELY from ``node_of`` on purpose:
    state updates must derive from the cheap mask so that when a caller
    discards node_of (the fused drive loop does), XLA can dead-code-
    eliminate the per-task ``chosen`` gather chain — deriving the mask
    from ``node_of >= 0`` instead keeps that gather live and costs ~8x
    on the 1M north star.

    Tiny class counts (the benchmark graphs, K <= 4) statically unroll —
    a scan's dynamic demand slice blocks fusion inside the drive
    while_loop. Larger counts scan (class as data: no recompile as
    classes accumulate).
    """
    import jax.numpy as jnp
    from jax import lax

    C = ready.shape[0]
    K = demands.shape[0]
    node_of0 = jnp.full((C,), -1, dtype=jnp.int32)
    assigned0 = jnp.zeros((C,), dtype=bool)
    release0 = jnp.zeros_like(avail)

    if K <= 4:
        node_of, assigned, release = node_of0, assigned0, release0
        for c in range(K):
            members = ready & (cls == c)
            assign_mask, chosen, avail, per_node = _assign_class_traced(
                members, demands[c], avail, cap, threshold, n_nodes,
                batch_cap,
                None if class_mask is None else class_mask[c],
                None if class_spread is None else class_spread[c])
            node_of = jnp.where(assign_mask, chosen, node_of)
            assigned = assigned | assign_mask
            release = release + per_node[:, None] * demands[c][None, :]
        return node_of, assigned, avail, release

    def step(carry, c):
        node_of, assigned, avail, release = carry
        members = ready & (cls == c)
        assign_mask, chosen, avail, per_node = _assign_class_traced(
            members, demands[c], avail, cap, threshold, n_nodes, batch_cap,
            None if class_mask is None else class_mask[c],
            None if class_spread is None else class_spread[c])
        node_of = jnp.where(assign_mask, chosen, node_of)
        assigned = assigned | assign_mask
        release = release + per_node[:, None] * demands[c][None, :]
        return (node_of, assigned, avail, release), None

    (node_of, assigned, avail, release), _ = lax.scan(
        step, (node_of0, assigned0, avail, release0),
        jnp.arange(K, dtype=jnp.int32))
    return node_of, assigned, avail, release


def _make_drive_loop(tick, cls, pin, demands, cap, src, dst, max_ticks):
    """while_loop driving the instant tick to DAG completion (shared by
    _jit_drive and _jit_bench so the loop cannot diverge between them)."""
    import jax.numpy as jnp
    from jax import lax

    def drive(state, indeg, avail, consumed):
        def cond(carry):
            state, indeg, avail, consumed, ticks = carry
            return (state == WAITING).any() & (ticks < max_ticks)

        def body(carry):
            state, indeg, avail, consumed, ticks = carry
            state, indeg, avail, _node_of, consumed = tick(
                state, indeg, cls, pin, demands, avail, cap, src, dst,
                consumed)
            return (state, indeg, avail, consumed, ticks + 1)

        return lax.while_loop(
            cond, body, (state, indeg, avail, consumed, jnp.int32(0)))

    return drive


@functools.lru_cache(maxsize=None)
def _jit_assign(threshold: float):
    """Jitted assignment over a compacted ready batch (runtime big-batch
    path). Inputs: ready_cls [Kpad] int32 (class per ready task), valid
    [Kpad] bool, demands [K,R], avail/cap [N,R]. Returns (node_of [Kpad]
    int32, -1 = not assigned; new avail). jit specializes on the padded
    shapes; the class axis is scanned, so class count only recompiles at
    power-of-two bucket boundaries (the padding done by jax_assign)."""
    import jax

    def assign(ready_cls, valid, demands, avail, cap, class_mask,
               class_spread):
        kpad = ready_cls.shape[0]
        node_of, _assigned, avail, _release = _scan_classes(
            valid, ready_cls, demands, avail, cap, threshold,
            avail.shape[0], kpad, class_mask, class_spread)
        return node_of, avail

    return jax.jit(assign)


def jax_assign(ready_cls: np.ndarray, demands: np.ndarray, avail: np.ndarray,
               cap: np.ndarray, threshold: float,
               class_mask: Optional[np.ndarray] = None,
               class_spread: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Pad the ready batch AND the class axis to power-of-two buckets
    (bounds recompiles to O(log) in both) and run the jitted assignment.
    Same contract as assign_np given ready_cls = cls[ready_idx]."""
    k = len(ready_cls)
    kpad = 1 << max(9, (k - 1).bit_length())
    padded = np.zeros(kpad, dtype=np.int32)
    padded[:k] = ready_cls
    valid = np.zeros(kpad, dtype=bool)
    valid[:k] = True
    num_classes = int(demands.shape[0])
    n_nodes = avail.shape[0]
    kcls = 1 << max(0, (num_classes - 1).bit_length())
    demands = demands.astype(np.float32)
    if class_mask is None:
        class_mask = np.ones((num_classes, n_nodes), dtype=bool)
    if class_spread is None:
        class_spread = np.zeros(num_classes, dtype=bool)
    if kcls > num_classes:
        pad_k = kcls - num_classes
        demands = np.concatenate(
            [demands, np.zeros((pad_k, demands.shape[1]),
                               dtype=np.float32)], axis=0)
        class_mask = np.concatenate(
            [class_mask, np.zeros((pad_k, n_nodes), dtype=bool)], axis=0)
        class_spread = np.concatenate(
            [class_spread, np.zeros(pad_k, dtype=bool)])
    fn = _jit_assign(float(threshold))
    node_of, new_avail = fn(padded, valid, demands,
                            avail.astype(np.float32), cap.astype(np.float32),
                            class_mask.astype(bool),
                            class_spread.astype(bool))
    return np.asarray(node_of)[:k], np.asarray(new_avail)


def _make_instant_tick(threshold: float):
    """Traced instant-completion tick body shared by the single-tick entry
    point and the fused on-device drive loop: ready-set -> assignment ->
    instant completion -> resource release -> edge firing.

    ``pin[t] >= 0`` assigns task t straight to that node with no capacity
    partition — the batched analog of the reference's actor-call path,
    where calls go directly to the actor's leased worker and never touch
    the scheduler (ray: src/ray/core_worker/transport/ —
    ActorTaskSubmitter submits over the actor's own queue). Pinned tasks
    should use an all-zero demand class: the actor's resources were
    acquired once at creation, not per call.
    """
    import jax
    import jax.numpy as jnp

    def tick(state, indeg, cls, pin, demands, avail, cap, src, dst, consumed):
        C = state.shape[0]
        ready = (state == WAITING) & (indeg <= 0)
        pinned = ready & (pin >= 0)
        node_of = jnp.where(pinned, pin, jnp.int32(-1))
        state = jnp.where(pinned, jnp.int8(RUNNING), state)
        ready = ready & ~pinned
        nof, assigned, avail, release = _scan_classes(
            ready, cls, demands, avail, cap, threshold, avail.shape[0], C)
        node_of = jnp.where(assigned, nof, node_of)
        state = jnp.where(assigned, jnp.int8(RUNNING), state)

        newly_done = state == RUNNING
        # instant completion releases exactly what assignment just took
        # (pinned tasks use zero-demand classes), so reuse the scan's
        # accumulated release matrix instead of recounting the task axis
        avail = jnp.minimum(avail + release, cap)
        state = jnp.where(newly_done, jnp.int8(DONE), state)
        done = state == DONE
        fire = done[src] & ~consumed
        # builders emit dst sorted ascending -> no sort inside segment_sum
        dec = jax.ops.segment_sum(fire.astype(jnp.int32), dst,
                                  num_segments=C, indices_are_sorted=True)
        indeg = indeg - dec
        consumed = consumed | fire
        return state, indeg, avail, node_of, consumed

    return tick


@functools.lru_cache(maxsize=None)
def _jit_drive(threshold: float, max_ticks: int, donate: bool = True):
    """Whole-DAG drive fused into ONE device program: lax.while_loop over
    the instant-completion tick. One dispatch + one host sync for the
    entire graph — this is the north-star measurement path (per-tick host
    round-trips would otherwise dominate on a tunneled/remote chip)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    tick = _make_instant_tick(threshold)

    def drive(state, indeg, cls, pin, demands, avail, cap, src, dst,
              consumed):
        loop = _make_drive_loop(tick, cls, pin, demands, cap, src, dst,
                                max_ticks)
        return loop(state, indeg, avail, consumed)

    return jax.jit(drive, donate_argnums=(0, 1, 9) if donate else ())


def jax_drive(state, indeg, cls, pin, demands, avail, cap, src, dst,
              consumed, *, num_classes: int, threshold: float,
              max_ticks: int, donate: bool = True):
    """Run the fused on-device DAG drive; returns (state, ..., ticks).

    CONTRACT: ``dst`` must be sorted ascending (the completion wave uses
    segment_sum(indices_are_sorted=True); unsorted dst silently corrupts
    indegrees). benchmarks._device_state enforces this by sorting.

    donate=False keeps the input buffers alive so the same device state
    can be re-driven (benchmark repeats without re-transferring)."""
    del num_classes  # class count is now the demands array's leading dim
    fn = _jit_drive(float(threshold), int(max_ticks), bool(donate))
    return fn(state, indeg, cls, pin, demands, avail, cap, src, dst,
              consumed)


@functools.lru_cache(maxsize=None)
def _jit_bench(threshold: float, max_ticks: int, k_reps: int):
    """K whole-DAG drives chained by true data dependence, in ONE program.

    Benchmark measurement core. Each repetition re-initializes the graph
    state from the originals PLUS an all-zero value computed from the
    previous repetition's outputs (``prev_state == RUNNING`` is always
    false after a completed drive, but XLA cannot prove that), so the
    compiler can neither CSE the repetitions nor hoist them out of the
    loop, and the executions serialize. Fetching the returned tick-count
    scalar forces genuine completion of all K drives — the only reliable
    completion signal on transports whose block_until_ready acks early.
    Cost model: T(K) = round_trip + K * drive_time; run at two K values
    and difference to cancel the round trip.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    tick = _make_instant_tick(threshold)

    def bench(state0, indeg0, cls, pin, demands, avail0, cap, src, dst,
              consumed0):
        drive = _make_drive_loop(tick, cls, pin, demands, cap, src, dst,
                                 max_ticks)

        def outer(i, carry):
            prev_state, _pi, _pa, _pc, total = carry
            opaque = (prev_state == RUNNING).astype(jnp.int32)  # all zeros
            state = (jnp.full_like(prev_state, WAITING)
                     + opaque.astype(jnp.int8))
            indeg = indeg0 + opaque
            avail = avail0 + _pa * 0.0  # original avail + opaque zero
            consumed = consumed0 | (prev_state == RUNNING)[src]
            state, indeg, avail, consumed, t = drive(
                state, indeg, avail, consumed)
            return (state, indeg, avail, consumed, total + t)

        state, indeg, avail, consumed, total = lax.fori_loop(
            0, k_reps, outer,
            (state0, indeg0, avail0, consumed0, jnp.int32(0)))
        return total, state

    return jax.jit(bench)


def jax_bench(state, indeg, cls, pin, demands, avail, cap, src, dst,
              consumed, *, num_classes: int, threshold: float,
              max_ticks: int, k_reps: int):
    """Run K chained drives; returns (total_ticks scalar, final state).

    CONTRACT: ``dst`` must be sorted ascending (see jax_drive)."""
    del num_classes  # class count is now the demands array's leading dim
    fn = _jit_bench(float(threshold), int(max_ticks), int(k_reps))
    return fn(state, indeg, cls, pin, demands, avail, cap, src, dst,
              consumed)


@functools.lru_cache(maxsize=None)
def _jit_tick(threshold: float, instant_completion: bool):
    """Build a jitted whole-graph tick: ready-set -> per-class assignment
    -> (optionally) instant completion + edge firing.

    ``instant_completion=True`` is the benchmark/simulation mode: assigned
    tasks complete within the tick and their out-edges fire, so one tick
    advances one wave of the DAG. The runtime scheduler uses
    ``instant_completion=False`` and reports completions from real
    executions between ticks.
    """
    import jax
    import jax.numpy as jnp

    if instant_completion:
        tick = _make_instant_tick(threshold)
        return jax.jit(tick, donate_argnums=(0, 1, 9))

    def tick(state, indeg, cls, pin, demands, avail, cap, src, dst, consumed):
        C = state.shape[0]
        ready = (state == WAITING) & (indeg <= 0)
        pinned = ready & (pin >= 0)
        node_of = jnp.where(pinned, pin, jnp.int32(-1))
        state = jnp.where(pinned, jnp.int8(RUNNING), state)
        ready = ready & ~pinned
        nof, assigned, avail, _release = _scan_classes(
            ready, cls, demands, avail, cap, threshold, avail.shape[0], C)
        node_of = jnp.where(assigned, nof, node_of)
        state = jnp.where(assigned, jnp.int8(RUNNING), state)
        return state, indeg, avail, node_of, consumed

    return jax.jit(tick, donate_argnums=(0, 1, 9))


def jax_tick(state, indeg, cls, pin, demands, avail, cap, src, dst, consumed,
             *, num_classes: int, threshold: float,
             instant_completion: bool = False):
    """Run one jitted tick; shapes are static per (C, E, N, R, K) bucket.

    CONTRACT: ``dst`` must be sorted ascending (see jax_drive)."""
    del num_classes  # class count is now the demands array's leading dim
    fn = _jit_tick(float(threshold), bool(instant_completion))
    return fn(state, indeg, cls, pin, demands, avail, cap, src, dst, consumed)
