"""TensorScheduler — batched array-resident scheduler (the north star).

Replaces the per-event O(1) decisions of EventScheduler (and of the
reference's ClusterTaskManager / ILocalTaskManager,
ray: src/ray/raylet/scheduling/cluster_task_manager.cc,
local_task_manager.cc) with per-tick batched decisions over the whole
pending set, held as arrays (see kernels.py for the decision kernels).

Architecture:
  - submit()/notify_*() only enqueue events (O(1), lock-held briefly)
    and wake the tick thread.
  - The tick thread drains all queued events, updates the task arena
    arrays in bulk, computes the ready set + assignments with one
    batched kernel call, and dispatches outside the lock.
  - Dependencies are tracked as an ``indegree`` vector plus a host-side
    ``object -> waiting slots`` index; object-ready events decrement
    indegrees with one scatter per tick.

Backends: numpy ticks by default (lowest latency at interactive sizes);
the jax jitted kernel takes over for large ready batches
(config sched_jax_min_batch) and for the benchmark graphs.

The EventScheduler is kept as the semantics oracle: property tests run
identical task graphs through both and assert the same completion
semantics and capacity invariants.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.scheduler import kernels
from ray_tpu._private.scheduler.base import PendingTask, SchedulerBase
from ray_tpu._private.scheduler.kernels import DONE, FREE, RUNNING, WAITING
from ray_tpu._private.scheduler.local import NodeState
from ray_tpu._private.task_spec import custom_resources, resources_to_vector


class TensorScheduler(SchedulerBase):
    def __init__(self, nodes: List[NodeState],
                 dispatcher: Callable[[PendingTask], None],
                 store_contains: Optional[Callable[[ObjectID], bool]] = None,
                 initial_capacity: Optional[int] = None):
        self._dispatch = dispatcher
        # batch lease-grant path: a dispatcher OBJECT may expose
        # dispatch_many(list) so one tick's grants ship per-worker in
        # single pipe messages (plain callables dispatch one at a time)
        self._dispatch_many = getattr(dispatcher, "dispatch_many", None)
        self._store_contains = store_contains or (lambda oid: False)
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        # True only while the tick thread is parked in wait(): producers
        # skip the notify syscall when the loop is already awake (under
        # load it almost always is, and notify-per-event was measurable)
        self._sleeping = False

        n_res = GLOBAL_CONFIG.sched_num_resources
        self._cap = np.zeros((0, n_res), dtype=np.float32)
        self._avail = np.zeros((0, n_res), dtype=np.float32)
        self._node_states: List[NodeState] = []
        # dispatch-window bookkeeping (reference: raylet dispatch queue):
        # outstanding = dispatched-not-finished per node; win_cap > 0
        # lets simple CPU tasks lease beyond avail up to that many
        # outstanding, queueing at the node's pool
        self._outstanding = np.zeros(0, dtype=np.int64)
        self._win_cap = np.zeros(0, dtype=np.int64)
        for n in nodes:
            self._append_node_locked(n)

        # arena slots grow by doubling; the knob sets the starting size
        # (bigger = fewer regrow copies on sustained load, more resident
        # memory up front)
        c = (initial_capacity if initial_capacity is not None
             else GLOBAL_CONFIG.sched_arena_capacity)
        self._state = np.zeros(c, dtype=np.int8)
        self._indeg = np.zeros(c, dtype=np.int32)
        self._cls = np.zeros(c, dtype=np.int32)
        self._node_of = np.full(c, -1, dtype=np.int32)
        # True for slots leased through the dispatch window: they hold a
        # pool-queue position, not node resources, so completion must
        # not release what was never charged
        self._windowed = np.zeros(c, dtype=bool)
        self._free: collections.deque = collections.deque(range(c))

        self._tasks: Dict[int, PendingTask] = {}       # slot -> task
        self._slot_of: Dict[TaskID, int] = {}
        # id the slot was admitted under: spec.task_id mutates on retry, so
        # release must use the admission-time id, not spec.task_id
        self._tid_of: Dict[int, TaskID] = {}
        self._waiters: Dict[ObjectID, List[int]] = {}  # oid -> slots
        self._deps_of: Dict[int, List[ObjectID]] = {}  # slot -> pending oids
        # slot -> ((ObjectID, nbytes), ...) stamped at submit: drives the
        # locality column. A dict (not an array) because only tasks with
        # ObjectRef args under remote clusters carry it — usually sparse.
        self._argsz: Dict[int, Tuple] = {}

        self._class_index: Dict[Tuple, int] = {}
        self._demands = np.zeros((0, n_res), dtype=np.float32)
        # node-eligibility masks per scheduling class (placement groups,
        # SPREAD, node affinity). Rebuilt lazily when classes or the node
        # set change; the kernels consume them as [K,N] / [K] arrays.
        self._class_place: List[Tuple] = []
        # named custom demands per class (per-name feasibility lives in
        # the eligibility masks; the demand MATRIX keeps a fixed width)
        self._class_custom: List[Dict[str, float]] = []
        # dispatch-window eligibility per class: plain CPU<=1 demand,
        # default/spread placement, no named resources — the shape whose
        # real concurrency bound is "one worker pipe each"
        self._class_window_ok: List[bool] = []
        self._class_mask = np.zeros((0, 0), dtype=bool)
        self._class_spread = np.zeros(0, dtype=bool)
        self._mask_dirty = False

        self._submit_q: collections.deque = collections.deque()
        self._ready_obj_q: collections.deque = collections.deque()
        self._finish_q: collections.deque = collections.deque()

        self._num_submitted = 0
        self._num_dispatched = 0
        self._num_finished = 0
        self._num_ticks = 0
        self._last_tick = 0.0  # monotonic stamp of the last coalesced tick
        # auto-backend calibration: the jitted device path only wins when
        # the device round trip is cheap (it is NOT under a tunneled chip,
        # where one dispatch costs ~50 ms). "cold" -> background warmup on
        # first large batch -> timed head-to-head -> "jax" | "numpy".
        self._calib_state = "cold"   # cold | warming | jax | numpy
        self._np_cost = 0.0          # EWMA of assign_np wall time (s)
        self._jax_cost = float("inf")
        self._dirty = False  # schedulability changed without a queued event
        self._shutdown = False
        self._tick_thread = threading.Thread(
            target=self._tick_loop, daemon=True, name="ray_tpu_sched_tick")
        self._tick_thread.start()

    # -- SchedulerBase -----------------------------------------------------
    def submit(self, task: PendingTask) -> None:
        with self._wake:
            self._submit_q.append(task)
            self._num_submitted += 1
            if self._sleeping:
                self._wake.notify()

    def submit_many(self, tasks: List[PendingTask]) -> None:
        """One lock acquire + one wakeup for the whole batch (the
        per-submit lock/notify pair is most of submit()'s cost once
        callers batch)."""
        with self._wake:
            self._submit_q.extend(tasks)
            self._num_submitted += len(tasks)
            if self._sleeping:
                self._wake.notify()

    def notify_object_ready(self, object_id: ObjectID) -> None:
        with self._wake:
            self._ready_obj_q.append(object_id)
            if self._sleeping:
                self._wake.notify()

    def notify_task_finished(self, task_id: TaskID, node_index: int,
                             resources: Dict[str, float]) -> None:
        with self._wake:
            self._finish_q.append((task_id, node_index, resources))
            self._num_finished += 1
            if self._sleeping:
                self._wake.notify()

    def notify_batch(self, ready_objects, finished) -> None:
        with self._wake:
            self._ready_obj_q.extend(ready_objects)
            self._finish_q.extend(finished)
            self._num_finished += len(finished)
            if self._sleeping:
                self._wake.notify()

    def cancel(self, task_id: TaskID) -> bool:
        with self._wake:
            # not yet admitted: remove straight from the submission queue
            for task in self._submit_q:
                if task.spec.task_id == task_id:
                    task.cancelled = True
                    self._submit_q.remove(task)
                    return True
            slot = self._slot_of.get(task_id)
            if slot is None or self._state[slot] not in (WAITING,):
                return False
            task = self._tasks.get(slot)
            if task is not None:
                task.cancelled = True
            self._release_slot_locked(slot)
            return True

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            waiting_mask = self._state == WAITING
            dep_blocked = waiting_mask & (self._indeg > 0)
            ready_mask = waiting_mask & (self._indeg <= 0)
            # infeasible = ready but no node's *capacity* can ever hold it;
            # feasibility depends only on the class, so compute per class
            # (K x N) and count ready slots per class — O(K*N + C)
            if self._demands.shape[0] and ready_mask.any():
                class_feasible = (self._cap[None, :, :]
                                  >= self._demands[:, None, :]).all(
                                      axis=2).any(axis=1)  # [K]
                ready_cls_counts = np.bincount(
                    self._cls[ready_mask],
                    minlength=self._demands.shape[0])
                infeasible = int(ready_cls_counts[~class_feasible].sum())
            else:
                infeasible = 0
            return {
                "submitted": self._num_submitted,
                "dispatched": self._num_dispatched,
                "finished": self._num_finished,
                "local_dispatch": self._num_local_dispatch,
                "spillback": self._num_spillback,
                "ticks": self._num_ticks,
                "waiting_deps": int(dep_blocked.sum()),
                "ready_queue": int(ready_mask.sum()) - infeasible,
                "running": int((self._state == RUNNING).sum()),
                "infeasible": infeasible,
                "nodes": [
                    {"available": self._avail[i].tolist(),
                     "capacity": self._cap[i].tolist(),
                     "is_bundle": self._node_states[i].is_bundle,
                     "custom": dict(self._node_states[i].custom),
                     "custom_avail":
                         dict(self._node_states[i].custom_avail)}
                    for i in range(len(self._node_states))
                ],
            }

    def shutdown(self) -> None:
        with self._wake:
            self._shutdown = True
            if self._sleeping:
                self._wake.notify()
        self._tick_thread.join(timeout=2.0)

    def pending_entries(self, started=None) -> List[Tuple[Any, List[ObjectID]]]:
        """(spec, unresolved deps) for every not-yet-dispatched task —
        the resubmittable half of a control-plane snapshot. ``started``
        (task_id -> bool) lets the caller also reclaim window-leased
        slots that are still queued behind a worker (leased != running
        for a dispatch-window grant)."""
        with self._lock:
            out = []
            for slot, task in self._tasks.items():
                if self._state[slot] == WAITING:
                    out.append((task.spec, list(task.deps)))
                elif (self._windowed[slot] and started is not None
                      and self._state[slot] == RUNNING
                      and not started(task.spec.task_id)):
                    out.append((task.spec, list(task.deps)))
            out.extend((t.spec, list(t.deps)) for t in self._submit_q)
            return out

    def device_state_snapshot(self) -> Dict[str, Any]:
        """Copies of the scheduler's resident arrays, trimmed to the
        occupied slot prefix (SURVEY §5: the checkpoint includes the
        device tensors, not just host tables). FORENSIC data: restore
        resubmits from the task SPECS and re-admission rebuilds these
        arrays — raw slots are meaningless in a new session without the
        old slot maps, so they are recorded for inspection/debugging of
        the snapshot moment, not replayed."""
        with self._lock:
            hi = int(np.flatnonzero(self._state != FREE).max(initial=-1)
                     ) + 1
            return {
                "state": self._state[:hi].copy(),
                "indeg": self._indeg[:hi].copy(),
                "cls": self._cls[:hi].copy(),
                "node_of": self._node_of[:hi].copy(),
                "demands": self._demands.copy(),
                "avail": self._avail.copy(),
                "cap": self._cap.copy(),
            }

    def task_table(self) -> List[Dict[str, Any]]:
        """Live tasks straight off the scheduler arrays (the survey's
        'list tasks that reads back the scheduler tensors'): one row per
        occupied arena slot, state decoded from the state vector."""
        with self._lock:
            rows = []
            for slot, task in self._tasks.items():
                st = int(self._state[slot])
                state = {WAITING: ("PENDING_ARGS" if self._indeg[slot] > 0
                                   else "PENDING_NODE"),
                         RUNNING: "RUNNING",
                         DONE: "FINISHED",
                         FREE: "FREE"}.get(st, str(st))
                spec = task.spec
                rows.append({
                    "task_id": self._tid_of.get(slot, spec.task_id).hex(),
                    "name": spec.name,
                    "state": state,
                    "node_index": int(self._node_of[slot]),
                    "attempt": spec.attempt_number,
                    "scheduling_class": int(self._cls[slot]),
                })
            # queued-but-unadmitted submissions
            for task in self._submit_q:
                rows.append({
                    "task_id": task.spec.task_id.hex(),
                    "name": task.spec.name,
                    "state": "QUEUED",
                    "node_index": -1,
                    "attempt": task.spec.attempt_number,
                    "scheduling_class": -1,
                })
            return rows

    def node_state(self, index: int) -> Optional[NodeState]:
        with self._lock:
            return self._node_states[index] \
                if 0 <= index < len(self._node_states) else None

    def try_allocate(self, index: int, resources: Dict[str, float]) -> bool:
        """Directly charge a row if it fits (actor restart-elsewhere:
        the replacement node must account for the actor's resources)."""
        with self._wake:
            if not (0 <= index < len(self._node_states)):
                return False
            vec = np.asarray(resources_to_vector(resources),
                             dtype=np.float32)[:self._cap.shape[1]]
            custom = custom_resources(resources)
            ns = self._node_states[index]
            if self._cap[index].any() \
                    and (self._avail[index] >= vec - 1e-6).all() \
                    and ns.has_custom(custom) and ns.fits_custom(custom):
                self._avail[index] -= vec
                ns.allocate(tuple(vec.tolist()))
                ns.allocate_custom(custom)
                return True
            return False

    def node_count(self) -> int:
        with self._lock:
            return len(self._node_states)

    # -- node management ---------------------------------------------------
    def add_node(self, node: NodeState, wake: bool = True) -> int:
        """wake=False appends the row WITHOUT waking the tick thread:
        callers that must finish wiring (e.g. registering the node's
        worker pool) before any task can dispatch to the row call
        poke() afterwards — dispatching into a half-registered node
        races pool_for_node() to None."""
        with self._wake:
            idx = self._append_node_locked(node)
            if wake:
                self._dirty = True
                if self._sleeping:
                    self._wake.notify()
            return idx

    def poke(self) -> None:
        """Wake the tick thread (schedulability may have changed)."""
        with self._wake:
            self._dirty = True
            if self._sleeping:
                self._wake.notify()

    def remove_node(self, node_index: int) -> None:
        with self._wake:
            self._cap[node_index] = 0.0
            self._avail[node_index] = 0.0
            self._node_states[node_index].capacity = [0.0] * self._cap.shape[1]
            self._node_states[node_index].available = [0.0] * self._cap.shape[1]
            # a dead node's named resources leave the cluster with it
            self._node_states[node_index].custom = {}
            self._node_states[node_index].custom_avail = {}
            # soft-affinity classes pinned to this node must re-resolve
            # (dead target -> fall back to the default node set)
            self._mask_dirty = True
            self._dirty = True
            if self._sleeping:
                self._wake.notify()

    def _append_node_locked(self, node: NodeState) -> int:
        vec = np.zeros((1, self._cap.shape[1] if self._cap.size else
                        GLOBAL_CONFIG.sched_num_resources), dtype=np.float32)
        for i, v in enumerate(node.capacity[:vec.shape[1]]):
            vec[0, i] = v
        self._cap = np.concatenate([self._cap, vec], axis=0)
        av = vec.copy()
        for i, v in enumerate(node.available[:vec.shape[1]]):
            av[0, i] = v
        self._avail = np.concatenate([self._avail, av], axis=0)
        self._node_states.append(node)
        self._outstanding = np.concatenate(
            [self._outstanding, np.zeros(1, dtype=np.int64)])
        win = 0
        if node.window_factor > 1 and not node.is_bundle:
            win = int(node.window_factor * max(vec[0, 0], 1.0))
        self._win_cap = np.concatenate(
            [self._win_cap, np.asarray([win], dtype=np.int64)])
        self._mask_dirty = True
        return len(self._node_states) - 1

    # -- placement groups ---------------------------------------------------
    def pack_snapshot(self):
        """(avail [N,R], cap [N,R], row indices) over PHYSICAL nodes only —
        the input to the placement-group bin-pack solve."""
        with self._wake:
            rows = [i for i, n in enumerate(self._node_states)
                    if not n.is_bundle]
            return (self._avail[rows].copy(), self._cap[rows].copy(), rows)

    def add_bundle_nodes(self, pg_id, placements) -> Optional[List[int]]:
        """Atomically reserve bundles: placements = [(parent_row,
        demand_vec, custom_dict), ...] in bundle order; all-or-nothing
        (the 2-phase prepare/commit of the reference's
        GcsPlacementGroupScheduler,
        ray: src/ray/raylet/placement_group_resource_manager.cc). Returns
        new bundle rows or None if availability moved since the pack."""
        with self._wake:
            n_res = self._cap.shape[1]
            need: Dict[int, np.ndarray] = {}
            for parent, vec, _custom in placements:
                acc = need.setdefault(parent, np.zeros(n_res, np.float32))
                acc[:len(vec)] += np.asarray(vec, dtype=np.float32)[:n_res]
            for parent, total in need.items():
                if not (self._avail[parent] >= total - 1e-6).all():
                    return None
            rows = []
            for bindex, (parent, vec, custom) in enumerate(placements):
                v = np.zeros(n_res, np.float32)
                v[:len(vec)] = np.asarray(vec, dtype=np.float32)[:n_res]
                self._avail[parent] -= v
                self._node_states[parent].allocate(tuple(v.tolist()))
                self._node_states[parent].allocate_custom(custom)
                row = self._append_node_locked(NodeState(
                    tuple(v.tolist()),
                    node_id=self._node_states[parent].node_id,
                    pg_id=pg_id, bundle_index=bindex, parent=parent,
                    custom_resources=custom))
                rows.append(row)
            self._dirty = True
            if self._sleeping:
                self._wake.notify()
            return rows

    def drain_pg_tasks(self, pg_id) -> List[PendingTask]:
        """Remove and return every not-yet-dispatched task targeting the
        group (its rows are gone; leaving them queued would hang their
        callers forever)."""
        pid = pg_id.binary()

        def match(task) -> bool:
            p = task.spec.placement_group_id
            return p is not None and p.binary() == pid

        out: List[PendingTask] = []
        with self._wake:
            kept = collections.deque()
            while self._submit_q:
                t = self._submit_q.popleft()
                (out if match(t) else kept).append(t)
            self._submit_q.extend(kept)
            for slot, task in list(self._tasks.items()):
                if self._state[slot] == WAITING and match(task):
                    out.append(task)
                    self._release_slot_locked(slot)
        return out

    def remove_pg(self, pg_id) -> None:
        """Release a group's bundle rows back to their parents.

        Only the FREE part of each bundle returns immediately; capacity
        held by still-running tasks stays charged to the (now defunct)
        row and flows back to the parent task-by-task as completions
        drain — releasing it all at once would overcommit the parent.
        Row indices stay valid."""
        with self._wake:
            for i, ns in enumerate(self._node_states):
                if ns.pg_id == pg_id and not ns.defunct \
                        and self._cap[i].any():
                    parent = ns.parent
                    free = self._avail[i].copy()
                    self._avail[parent] = np.minimum(
                        self._avail[parent] + free, self._cap[parent])
                    self._node_states[parent].release(tuple(free.tolist()))
                    # the UNUSED part of the bundle's named resources
                    # returns now; the in-use part follows task-by-task
                    # through the defunct completion path
                    self._node_states[parent].release_custom(ns.custom_avail)
                    in_use = self._cap[i] - free
                    self._cap[i] = in_use
                    self._avail[i] = 0.0
                    ns.capacity = in_use.tolist()
                    ns.available = [0.0] * self._cap.shape[1]
                    ns.defunct = True
            self._mask_dirty = True
            self._dirty = True
            if self._sleeping:
                self._wake.notify()

    # -- tick loop ---------------------------------------------------------
    def _tick_loop(self) -> None:
        # Every WAITING->schedulable transition arrives as a queued event
        # (object ready, task finished, node added), so the thread sleeps
        # until events exist — no polling of dep-blocked or saturated tasks.
        while True:
            with self._wake:
                while (not self._shutdown and not self._submit_q
                       and not self._ready_obj_q and not self._finish_q
                       and not self._dirty):
                    self._sleeping = True
                    self._wake.wait(timeout=0.5)
                    self._sleeping = False
                if self._shutdown:
                    return
                # tick coalescing floor: with sched_tick_interval_s > 0,
                # an event burst arriving right after a tick waits out
                # the remainder of the interval so the whole burst lands
                # in ONE drain/assign cycle (0 = tick immediately)
                interval = GLOBAL_CONFIG.sched_tick_interval_s
                if interval > 0.0:
                    remaining = self._last_tick + interval - time.monotonic()
                    if remaining > 0:
                        self._wake.wait(timeout=remaining)
                    if self._shutdown:
                        return
                    self._last_tick = time.monotonic()
                self._dirty = False
                try:
                    snapshot = self._drain_events_locked()
                except Exception:
                    logger.exception(
                        "scheduler tick failed; state may be inconsistent")
                    snapshot = None
            to_dispatch: List[PendingTask] = []
            if snapshot is not None:
                try:
                    # assignment (and any jit compilation it triggers) runs
                    # OUTSIDE the lock: the tick thread is the only mutator
                    # of the scheduling arrays, so the snapshot stays
                    # coherent; cancel()/remove_node() races are validated
                    # at apply time
                    ready_idx, decisions, new_avail = self._assign(snapshot)
                    if ready_idx is not None:
                        with self._wake:
                            to_dispatch = self._apply_locked(
                                ready_idx, decisions)
                except Exception:
                    logger.exception("scheduler assignment failed")
            if to_dispatch and self._dispatch_many is not None:
                try:
                    self._dispatch_many(to_dispatch)
                except Exception:
                    logger.exception("batch dispatch failed")
            else:
                for task in to_dispatch:
                    try:
                        self._dispatch(task)
                    except Exception:
                        logger.exception("dispatch failed for %s",
                                         task.spec.task_id)

    def _drain_events_locked(self):
        self._num_ticks += 1

        # 1) admissions
        while self._submit_q:
            task = self._submit_q.popleft()
            slot = self._alloc_slot_locked()
            spec = task.spec
            self._tasks[slot] = task
            self._slot_of[spec.task_id] = slot
            self._tid_of[slot] = spec.task_id
            key = spec.scheduling_class()
            cidx = self._class_index.get(key)
            if cidx is None:
                cidx = len(self._class_index)
                self._class_index[key] = cidx
                vec = np.asarray(spec.resource_vector(), dtype=np.float32)
                d = np.zeros((1, self._cap.shape[1]), dtype=np.float32)
                w = min(len(vec), d.shape[1])
                d[0, :w] = vec[:w]
                self._demands = np.concatenate([self._demands, d], axis=0)
                place = spec.placement()
                custom = custom_resources(spec.resources)
                self._class_place.append(place)
                self._class_custom.append(custom)
                self._class_window_ok.append(
                    not custom
                    and place in (("default",), ("spread",))
                    and d[0, 0] <= 1.0
                    and not d[0, 1:].any())
                self._append_class_mask_locked(place, custom)
            self._cls[slot] = cidx
            pending_deps = []
            for dep in task.deps:
                if self._store_contains(dep):
                    continue
                self._waiters.setdefault(dep, []).append(slot)
                pending_deps.append(dep)
            self._indeg[slot] = len(pending_deps)
            if pending_deps:
                self._deps_of[slot] = pending_deps
            sizes = getattr(spec, "arg_sizes", None)
            if sizes:
                self._argsz[slot] = sizes
            self._state[slot] = WAITING

        # 2) object-ready wave (batched indegree scatter)
        dec_slots: List[int] = []
        waiters = self._waiters
        while self._ready_obj_q:
            oid = self._ready_obj_q.popleft()
            if waiters:
                w = waiters.pop(oid, None)
                if w:
                    dec_slots.extend(w)
        if dec_slots:
            np.subtract.at(self._indeg, np.asarray(dec_slots, dtype=np.int64), 1)
            te = self.task_events
            if te is not None:
                # slots whose last dependency just landed (dep-blocked
                # tasks only: no-dep admissions never enter dec_slots)
                tid_of = self._tid_of
                newly_ready = [tid_of[s] for s in set(dec_slots)
                               if self._state[s] == WAITING
                               and self._indeg[s] <= 0
                               and s in tid_of]
                if newly_ready:
                    te.record_ready_batch(newly_ready)

        # 3) completions: release resources, free slots
        while self._finish_q:
            task_id, node_index, resources = self._finish_q.popleft()
            slot = self._slot_of.get(task_id)
            was_windowed = False
            cidx = -1
            if slot is not None and self._state[slot] == RUNNING:
                was_windowed = bool(self._windowed[slot])
                cidx = int(self._cls[slot])
                if 0 <= node_index < len(self._node_states):
                    self._outstanding[node_index] = max(
                        self._outstanding[node_index] - 1, 0)
                self._release_slot_locked(slot)
            if was_windowed:
                continue  # a window lease held no node resources
            if 0 <= node_index < len(self._node_states):
                if 0 <= cidx < len(self._class_custom):
                    # the class row IS the demand vector — skip the
                    # per-completion dict -> vector conversion
                    vec = self._demands[cidx]
                    custom = self._class_custom[cidx]
                else:
                    vec = np.asarray(resources_to_vector(resources),
                                     dtype=np.float32)[:self._cap.shape[1]]
                    custom = custom_resources(resources)
                ns = self._node_states[node_index]
                if ns.defunct:
                    # removed bundle: this task's share of the carved-out
                    # capacity returns to the parent now that it is free
                    parent = ns.parent
                    self._avail[parent] = np.minimum(
                        self._avail[parent] + vec, self._cap[parent])
                    self._node_states[parent].release(tuple(vec))
                    self._node_states[parent].release_custom(custom)
                    self._cap[node_index] = np.maximum(
                        self._cap[node_index] - vec, 0.0)
                    ns.capacity = self._cap[node_index].tolist()
                else:
                    self._avail[node_index] = np.minimum(
                        self._avail[node_index] + vec, self._cap[node_index])
                    ns.release(tuple(vec))
                    ns.release_custom(custom)

        # snapshot for the out-of-lock assignment pass
        ready_idx = np.flatnonzero((self._state == WAITING) & (self._indeg <= 0))
        if len(ready_idx) == 0:
            return None
        plane = self.qos_plane
        tiers = None
        if plane is not None and len(ready_idx) > 0:
            # QoS assignment order: permuting ready_idx dispatches strict
            # tiers first with weighted fair-share between tenants inside
            # a tier (slot order, i.e. FIFO, within a tenant). The greedy
            # kernel honors array order WITHIN a scheduling class but
            # drains classes as groups, so ``tiers`` (priority per ready
            # position, descending) rides along: _assign chunks the batch
            # into per-tier runs so a lower tier never jumps a higher one
            # just because its class was registered first.
            tasks = self._tasks
            keys = []
            for slot in ready_idx:
                spec = tasks[int(slot)].spec
                keys.append((spec.priority, spec.tenant))
            order = plane.order(keys)
            ready_idx = ready_idx[np.asarray(order, dtype=np.int64)]
            tiers = np.asarray([keys[i][0] for i in order], dtype=np.int64)
        if self._mask_dirty:
            self._rebuild_masks_locked()
        locality = None
        outstanding = None
        if (self._argsz and GLOBAL_CONFIG.scheduler_locality
                and self.locations_of is not None):
            locality = self._locality_matrix_locked(ready_idx)
            if locality is not None:
                outstanding = self._outstanding.copy()
        return (ready_idx, self._cls[ready_idx].copy(), self._demands.copy(),
                self._avail.copy(), self._cap.copy(),
                self._class_mask.copy(), self._class_spread.copy(),
                locality, outstanding, tiers)

    def _locality_matrix_locked(self, ready_idx) -> Optional[np.ndarray]:
        """[len(ready_idx), N] resident-arg-bytes per candidate node,
        aligned to ready positions. A copy of unknown size weighs one
        byte so it still attracts. None when no ready task has any arg
        with a known remote location (the kernel's fast path)."""
        argsz = self._argsz
        locs_of = self.locations_of
        N = len(self._node_states)
        m = None
        for pos, slot in enumerate(ready_idx):
            sizes = argsz.get(int(slot))
            if not sizes:
                continue
            for oid, nbytes in sizes:
                for node in locs_of(oid):
                    if 0 <= node < N:
                        if m is None:
                            m = np.zeros((len(ready_idx), N),
                                         dtype=np.float64)
                        m[pos, node] += max(int(nbytes), 1)
        return m

    def _mask_row(self, place: Tuple,
                  custom: Dict[str, float] = {}) -> Tuple[np.ndarray, bool]:
        """(eligibility row [N], spread flag) for one placement descriptor
        (see TaskSpec.placement) + named custom demands against the
        current node set."""
        nodes = self._node_states
        N = len(nodes)
        non_bundle = np.asarray([not ns.is_bundle for ns in nodes],
                                dtype=bool) if N else np.zeros(0, bool)
        if custom:
            # per-NAME feasibility (quantity accounting rides the shared
            # CUSTOM capacity dimension)
            custom_ok = np.asarray([ns.has_custom(custom) for ns in nodes],
                                   dtype=bool) if N else np.zeros(0, bool)
        else:
            custom_ok = None

        def finish(row: np.ndarray, spread: bool):
            if custom_ok is not None:
                row = row & custom_ok
            return row, spread

        row = np.zeros(N, dtype=bool)
        kind = place[0]
        if kind == "pg":
            _, pid, bindex = place
            for i, ns in enumerate(nodes):
                if ns.pg_id is not None and not ns.defunct \
                        and ns.pg_id.binary() == pid \
                        and (bindex < 0 or ns.bundle_index == bindex):
                    row[i] = True
            return finish(row, False)
        if kind == "aff":
            nid, soft = place[1], place[2]
            found_alive = False
            for i, ns in enumerate(nodes):
                node_id = ns.node_id
                node_id = node_id.binary() \
                    if hasattr(node_id, "binary") else node_id
                if not ns.is_bundle and node_id == nid:
                    row[i] = True
                    if any(c > 0 for c in ns.capacity):
                        found_alive = True
            # soft affinity falls back only when the node is missing or
            # DEAD (a live-but-busy node means: wait for it)
            if soft and not found_alive:
                row = non_bundle.copy()
            return finish(row, False)
        return finish(non_bundle.copy(), kind == "spread")

    def _append_class_mask_locked(self, place: Tuple,
                                  custom: Dict[str, float] = {}) -> None:
        """Append one class row without a full K*N rebuild (classes are
        minted far more often than the node set changes)."""
        if self._mask_dirty:
            return  # a full rebuild is due anyway
        row, spread = self._mask_row(place, custom)
        if self._class_mask.shape[0] == 0:
            self._class_mask = row[None, :]
        else:
            self._class_mask = np.vstack([self._class_mask, row[None, :]])
        self._class_spread = np.append(self._class_spread, spread)

    def _rebuild_masks_locked(self) -> None:
        """Recompute [K,N] class->node eligibility + [K] spread flags
        (node set or PG membership changed)."""
        K = len(self._class_place)
        N = len(self._node_states)
        mask = np.zeros((K, N), dtype=bool)
        spread = np.zeros(K, dtype=bool)
        for k, place in enumerate(self._class_place):
            mask[k], spread[k] = self._mask_row(place,
                                                self._class_custom[k])
        self._class_mask = mask
        self._class_spread = spread
        self._mask_dirty = False

    def _assign(self, snapshot):
        """Batched assignment OUTSIDE the lock (jit compilation of the jax
        path can take seconds and must not block submit()/notify_*)."""
        (ready_idx, ready_cls, demands, avail, cap, class_mask,
         class_spread, locality, outstanding, tiers) = snapshot
        if tiers is not None and len(ready_idx) > 1 and tiers[0] != tiers[-1]:
            # QoS tier barrier: the kernels drain each scheduling class as
            # a group, which would let a lower-tier class registered first
            # absorb capacity ahead of a higher tier. Split the (already
            # tier-descending) batch into contiguous per-tier runs and
            # assign them in order, threading avail, so strict-tier order
            # holds ACROSS classes too. A handful of tiers per tick keeps
            # this cheap; qos=False never reaches here (tiers is None).
            bounds = np.flatnonzero(np.diff(tiers)) + 1
            node_parts = []
            cur_avail = avail
            for s, e in zip(np.r_[0, bounds], np.r_[bounds, len(ready_idx)]):
                sub = (ready_idx[int(s):int(e)], ready_cls[int(s):int(e)],
                       demands, cur_avail, cap, class_mask, class_spread,
                       locality[int(s):int(e)] if locality is not None
                       else None, outstanding, None)
                _, sub_nodes, cur_avail = self._assign(sub)
                node_parts.append(sub_nodes)
            return ready_idx, np.concatenate(node_parts), cur_avail
        backend = GLOBAL_CONFIG.sched_backend
        # class count no longer gates the device path: the kernel scans the
        # class axis (class as data), so many classes don't grow the program
        big = len(ready_idx) >= GLOBAL_CONFIG.sched_jax_min_batch
        # calibrate only once numpy ticks are slow enough that a device
        # dispatch (~1-2 ms minimum) could plausibly win — otherwise the
        # background jit compile steals CPU from the very workload the
        # ticks are serving (measurable on small hosts)
        if (backend == "auto" and big and self._calib_state == "cold"
                and self._np_cost > 2e-3):
            self._start_calibration(snapshot)
        use_jax = (backend == "jax"
                   or (backend == "auto" and big
                       and self._calib_state == "jax"))
        if locality is not None:
            # the device kernel has no locality column; ticks with
            # resident-arg scores run the numpy path (sparse in practice:
            # only batches containing tasks with remotely-located args)
            use_jax = False
        threshold = GLOBAL_CONFIG.sched_hybrid_threshold
        if use_jax:
            try:
                # compact the class axis to the classes PRESENT in this
                # batch: self._demands grows for process lifetime (one row
                # per unique scheduling class, never compacted), and the
                # kernel's scan length is its leading dim
                uniq, inv = np.unique(ready_cls, return_inverse=True)
                node_of_ready, new_avail = kernels.jax_assign(
                    inv.astype(np.int32), demands[uniq], avail, cap,
                    threshold, class_mask[uniq], class_spread[uniq])
            except Exception:
                logger.exception("jax assign failed; falling back to numpy")
                use_jax = False
        if not use_jax:
            t0 = time.perf_counter()
            cls_full = np.zeros(int(ready_idx.max()) + 1, dtype=np.int32)
            cls_full[ready_idx] = ready_cls
            node_of_ready, new_avail = kernels.assign_np(
                ready_idx, cls_full, demands, avail, cap, threshold,
                class_mask, class_spread,
                locality=locality, outstanding=outstanding,
                spill_depth=GLOBAL_CONFIG.locality_spillback_queue_depth)
            dt = time.perf_counter() - t0
            self._np_cost = 0.8 * self._np_cost + 0.2 * dt if self._np_cost else dt
        return ready_idx, node_of_ready, new_avail

    def _start_calibration(self, snapshot) -> None:
        """Warm + time the jitted device path off-thread; switch ``auto``
        to it only if a real tick beats the measured numpy tick. Under a
        remote/tunneled accelerator (e.g. an axon-proxied chip) a device
        dispatch costs tens of ms and numpy always wins; on a local chip
        with large ready batches the device kernel wins. Never stalls the
        tick loop: numpy serves until the verdict is in."""
        self._calib_state = "warming"
        # calibration times the device kernel, which has no locality
        # column — the trailing locality/outstanding entries are unused
        (ready_idx, ready_cls, demands, avail, cap, class_mask,
         class_spread) = snapshot[:7]
        threshold = GLOBAL_CONFIG.sched_hybrid_threshold

        def _calibrate() -> None:
            verdict = "numpy"
            try:
                uniq, inv = np.unique(ready_cls, return_inverse=True)
                args = (inv.astype(np.int32), demands[uniq], avail, cap,
                        threshold, class_mask[uniq], class_spread[uniq])
                kernels.jax_assign(*args)          # compile + warm
                t0 = time.perf_counter()
                kernels.jax_assign(*args)          # steady-state cost
                self._jax_cost = time.perf_counter() - t0
                # require a decisive win: the numpy EWMA is noisy (early
                # ticks include warmup) and the device path's dispatch
                # overhead recurs every tick, so a marginal victory in
                # one sample is not worth switching for
                if self._jax_cost < 0.5 * max(self._np_cost, 1e-6):
                    verdict = "jax"
            except Exception:
                logger.exception("jax tick calibration failed; numpy ticks")
            logger.info("sched auto backend: %s (jax %.3g s vs numpy %.3g s"
                        " per tick)", verdict, self._jax_cost, self._np_cost)
            self._calib_state = verdict

        threading.Thread(target=_calibrate, daemon=True,
                         name="ray_tpu_sched_calib").start()

    def _apply_locked(self, ready_idx, node_of_ready) -> List[PendingTask]:
        """Validate + apply out-of-lock decisions: a slot may have been
        cancelled and a node drained/removed since the snapshot."""
        out: List[PendingTask] = []
        # iterate ASSIGNED positions only: the unassigned tail can be the
        # whole backlog (tens of thousands), and a Python loop over it per
        # tick turns the apply step quadratic in the backlog size
        for pos in np.flatnonzero(np.asarray(node_of_ready) >= 0):
            node = int(node_of_ready[pos])
            slot = int(ready_idx[pos])
            if self._state[slot] != WAITING:
                continue  # cancelled (and maybe reused) since snapshot
            demand = self._demands[self._cls[slot]]
            # liveness first: a removed node zeroes its capacity, and a
            # zero-demand task would otherwise pass the fit check (0 >= 0)
            if not (self._cap[node] > 0).any():
                continue  # node removed since snapshot
            if self._node_states[node].defunct:
                continue  # bundle's group removed since snapshot
            if not (self._cap[node] >= demand).all():
                continue  # node shrunk since snapshot; next tick
            task = self._tasks.get(slot)
            if task is None or task.cancelled:
                self._release_slot_locked(slot)
                continue
            # per-NAME custom quantities are finer than the kernel's
            # aggregate CUSTOM dimension: re-validate + debit here (the
            # task waits a tick if its specific name is exhausted even
            # though the aggregate still fits)
            custom = self._class_custom[self._cls[slot]]
            ns = self._node_states[node]
            if custom and not ns.fits_custom(custom):
                # name exhausted though the aggregate fits: stay WAITING;
                # the completion that frees the name re-ticks the loop
                continue
            self._state[slot] = RUNNING
            self._node_of[slot] = node
            self._avail[node] -= demand
            self._outstanding[node] += 1
            task.node_index = node
            ns.allocate(tuple(demand.tolist()))
            ns.allocate_custom(custom)
            self._num_dispatched += 1
            out.append(task)
        self._window_pass_locked(ready_idx, node_of_ready, out)
        return out

    def _window_pass_locked(self, ready_idx, node_of_ready,
                            out: List[PendingTask]) -> None:
        """Dispatch-window leases (reference: the raylet's dispatch
        queue + worker backlog): ready tasks of simple CPU classes that
        found no free capacity may still lease onto a node whose
        OUTSTANDING count is under its window, queueing at the node's
        pool. No resources are charged (the pool's worker processes
        bound real concurrency); the slot is flagged so completion
        releases nothing."""
        if not self._win_cap.any():
            return
        room = self._win_cap - self._outstanding
        alive = self._cap[:, 0] > 0
        for i, ns in enumerate(self._node_states):
            if ns.defunct or ns.is_bundle:
                alive[i] = False
        room = np.where(alive, room, 0)
        total_room = int(room.sum())
        if total_room <= 0:
            return
        unassigned = np.flatnonzero(np.asarray(node_of_ready) < 0)
        if len(unassigned) == 0:
            return
        # node sequence with one entry per open window position
        nodes_seq = np.repeat(np.arange(len(room)), np.maximum(room, 0))
        taken = 0
        for pos in unassigned:
            if taken >= total_room:
                break
            slot = int(ready_idx[pos])
            if self._state[slot] != WAITING:
                continue
            if not self._class_window_ok[self._cls[slot]]:
                continue
            task = self._tasks.get(slot)
            if task is None or task.cancelled:
                self._release_slot_locked(slot)
                continue
            node = int(nodes_seq[taken])
            taken += 1
            self._state[slot] = RUNNING
            self._node_of[slot] = node
            self._windowed[slot] = True
            self._outstanding[node] += 1
            task.node_index = node
            self._num_dispatched += 1
            out.append(task)

    # -- slot lifecycle ----------------------------------------------------
    def _alloc_slot_locked(self) -> int:
        if not self._free:
            old = len(self._state)
            new = old * 2
            self._state = np.concatenate(
                [self._state, np.zeros(old, dtype=np.int8)])
            self._indeg = np.concatenate(
                [self._indeg, np.zeros(old, dtype=np.int32)])
            self._cls = np.concatenate(
                [self._cls, np.zeros(old, dtype=np.int32)])
            self._node_of = np.concatenate(
                [self._node_of, np.full(old, -1, dtype=np.int32)])
            self._windowed = np.concatenate(
                [self._windowed, np.zeros(old, dtype=bool)])
            self._free.extend(range(old, new))
        return self._free.popleft()

    def _release_slot_locked(self, slot: int) -> None:
        self._windowed[slot] = False
        self._argsz.pop(slot, None)
        self._tasks.pop(slot, None)
        tid = self._tid_of.pop(slot, None)
        if tid is not None and self._slot_of.get(tid) == slot:
            del self._slot_of[tid]
        for dep in self._deps_of.pop(slot, ()):
            lst = self._waiters.get(dep)
            if lst is not None:
                try:
                    lst.remove(slot)
                except ValueError:
                    pass
                if not lst:
                    self._waiters.pop(dep, None)
        self._state[slot] = FREE
        self._indeg[slot] = 0
        self._node_of[slot] = -1
        self._free.append(slot)
