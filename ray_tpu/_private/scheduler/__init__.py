"""Scheduling subsystem.

The policy boundary mirrors the reference's ClusterTaskManager /
ILocalTaskManager split (ray: src/ray/raylet/scheduling/): submission
enters through ``SchedulerBase.submit``; readiness tracking + node
assignment happen behind the boundary; dispatch callbacks execute tasks.

Two interchangeable implementations:
  - ``local.EventScheduler``   — per-event dict-based (reference-style
    O(1)-per-task decisions); the semantics oracle.
  - ``tensor.TensorScheduler`` — the north star: pending DAG held as
    device tensors, one fused tick computes ready set + assignments.
"""

from ray_tpu._private.scheduler.base import PendingTask, SchedulerBase  # noqa: F401
from ray_tpu._private.scheduler.local import EventScheduler  # noqa: F401
