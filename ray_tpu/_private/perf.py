"""End-to-end performance measurements for the headline bench.

Two honest numbers the scheduling-kernel bench (benchmarks.py) does not
capture:

1. ``e2e_task_throughput`` — real task throughput through the PUBLIC API
   (``f.remote()`` -> ``get``), including submit(), the arena, locks,
   dispatch, and result plumbing. This is the analog of the reference's
   ``ray microbenchmark`` single-node numbers
   (ray: python/ray/_private/ray_perf.py, SURVEY.md §6).

2. ``model_mfu`` — flagship-transformer training step time / tokens/s /
   MFU on the real chip, sized to use HBM. FLOPs come from the compiled
   program's own cost analysis (XLA's count), falling back to the
   analytic 6*N*D estimate. MFU = flops_per_step / step_time / peak,
   with peak looked up from the device kind.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

# bf16 peak FLOP/s per chip by device kind (public TPU specs).
_PEAK_FLOPS = (
    ("v6", 918e12),  # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e ("v5 litepod" variants report as v5e / v5lite)
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _peak_flops(device_kind: str) -> Optional[float]:
    kind = device_kind.lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return None


# PINNED CPU-fallback configs. When the chip is unreachable the bench
# runs these small fixed shapes instead of the flagship ones; they are
# frozen so fallback rounds stay comparable round-over-round — do NOT
# resize to "use the host better". bench.py records them in the output
# JSON so a reader can tell which shape produced a fallback number.
SMOKE_MODEL: Dict[str, int] = {
    "d_model": 256, "n_layers": 2, "n_heads": 8, "n_kv_heads": 4,
    "d_ff": 704, "vocab_size": 2048, "seq_len": 256, "batch_size": 4,
    "steps": 3,
}
SMOKE_DECODE: Dict[str, int] = {
    "vocab_size": 256, "d_model": 64, "n_layers": 2, "n_heads": 4,
    "n_kv_heads": 2, "d_ff": 128, "max_seq_len": 256, "batch": 2,
    "new_tokens": 16, "pages": 64,
}


def e2e_task_throughput(n_tasks: int = 10_000, mode: str = "thread",
                        scheduler: str = "tensor",
                        num_workers: int = 8,
                        batched: bool = False,
                        best_of: int = 1) -> Dict[str, Any]:
    """Submit n_tasks no-op tasks through the public API and get() them.

    Measures the full path: RemoteFunction._remote -> Worker.submit ->
    scheduler tick -> dispatch -> execution -> result store -> get.
    batched=True submits through map_remote (the vectorized path the
    libraries use); best_of>1 keeps the fastest trial (this host is a
    shared 1-CPU VM with ±30% noise between trials).
    """
    import resource

    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    ray_tpu.shutdown()
    sys_cfg = {"worker_mode": mode}
    ray_tpu.init(num_workers=num_workers, scheduler=scheduler,
                 _system_config=sys_cfg)
    try:
        @ray_tpu.remote
        def _noop():
            return None

        # Warm the pool / caches (process mode: function-blob push, worker
        # spin-up) so the measurement is steady-state.
        ray_tpu.get([_noop.remote() for _ in range(min(200, n_tasks))])
        if mode == "process":
            time.sleep(2.0)  # let late worker imports finish competing

        sched = worker_mod.global_worker.scheduler
        best = None
        for _ in range(max(1, best_of)):
            ticks0 = getattr(sched, "_num_ticks", 0)
            ru0 = resource.getrusage(resource.RUSAGE_SELF)
            t0 = time.perf_counter()
            if batched:
                refs = _noop.map_remote([()] * n_tasks)
            else:
                refs = [_noop.remote() for _ in range(n_tasks)]
            t_submit = time.perf_counter() - t0
            ray_tpu.get(refs)
            trial_dt = time.perf_counter() - t0
            ru1 = resource.getrusage(resource.RUSAGE_SELF)
            trial_ticks = getattr(sched, "_num_ticks", 0) - ticks0
            trial = (trial_dt, t_submit, ru0, ru1, trial_ticks)
            if best is None or trial_dt < best[0]:
                best = trial
            del refs
        dt, t_submit, ru0, ru1, ticks = best
    finally:
        ray_tpu.shutdown()
    driver_cpu = (ru1.ru_utime - ru0.ru_utime) + (ru1.ru_stime - ru0.ru_stime)
    return {
        "n_tasks": n_tasks,
        "mode": mode,
        "scheduler": scheduler,
        "seconds": dt,
        "tasks_per_sec": n_tasks / dt,
        # per-task host-overhead budget (microseconds)
        "budget_us": {
            "submit": round(t_submit / n_tasks * 1e6, 1),
            "driver_cpu_total": round(driver_cpu / n_tasks * 1e6, 1),
            "wall_total": round(dt / n_tasks * 1e6, 1),
        },
        "sched_ticks": ticks,
        "tasks_per_tick": round(n_tasks / max(ticks, 1), 1),
    }


def locality_ab(locality: bool, n_consumers: int = 8,
                arg_mb: float = 1.0,
                spill_depth: int = 32) -> Dict[str, Any]:
    """One arm of the locality-scheduling A/B: a 2-remote-node cluster,
    large objects produced on the SOURCE node, a consumer fanout free to
    run on either remote node.

    With ``locality=True`` the scheduler scores candidates by
    resident-arg-bytes and the consumers land (or wait, bounded by
    ``spill_depth``) on the source node — cross-node arg bytes stay
    near zero. With ``locality=False`` (the pre-PR placement) the
    least-loaded fill sends a batch of consumers to the sink node,
    each pulling its argument across. The SINK node is added first so
    the load-tiebreak favors it: the off arm genuinely moves bytes.

    Returns {sum, bytes_pulled, bytes_saved, seconds, hits, misses}.
    ``sum`` must match between arms (equal task results)."""
    import numpy as np

    import ray_tpu
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.cluster_utils import Cluster

    n = max(1, int(arg_mb * 1024 * 1024) // 8)
    ray_tpu.shutdown()
    c = Cluster(initialize_head=True,
                head_node_args=dict(
                    num_cpus=2, num_workers=2, scheduler="tensor",
                    _system_config={
                        "scheduler_locality": bool(locality),
                        "locality_spillback_queue_depth": spill_depth}))
    try:
        c.add_node(num_cpus=4, remote=True, resources={"r": 100.0})
        c.add_node(num_cpus=4, remote=True,
                   resources={"r": 100.0, "src": 100.0})
        c.wait_for_nodes()
        w = worker_mod.get_worker()

        @ray_tpu.remote(resources={"src": 1.0})
        def produce(i):
            import numpy as np  # task-side: don't close over the
            return np.full(n, float(i))  # driver's local module binding

        @ray_tpu.remote(resources={"r": 1.0})
        def consume(x):
            return float(x[0]) * len(x)

        refs = [produce.remote(i) for i in range(n_consumers)]
        for r in refs:
            ray_tpu.wait([r], timeout=120.0)
        ts = w.transfer_stats
        p0 = ts["bytes_pulled"]
        t0 = time.perf_counter()
        out = ray_tpu.get([consume.remote(r) for r in refs],
                          timeout=300.0)
        dt = time.perf_counter() - t0
        return {
            "locality": bool(locality),
            "n_consumers": n_consumers,
            "arg_mb": arg_mb,
            "sum": float(sum(out)),
            "bytes_pulled": int(ts["bytes_pulled"] - p0),
            "bytes_saved": int(ts["bytes_saved"]),
            "hits": int(ts["locality_hits"]),
            "misses": int(ts["locality_misses"]),
            "seconds": round(dt, 3),
        }
    finally:
        c.shutdown()


def head_bypass_ab(p2p: Optional[bool], n_calls: int = 40,
                   n_submit: int = 24,
                   head_tick_delay_s: float = 0.02) -> Dict[str, Any]:
    """One arm of the two-level/head-bypass A/B: a 2-remote-node
    cluster, an actor resident on node B, a caller task on node A
    issuing ``n_calls`` sequential actor calls.

    With ``p2p=True`` (``actor_p2p`` + ``local_dispatch`` on) the calls
    ship worker -> caller daemon -> peer daemon once the route
    resolves; only sequenced completion receipts reach the head. With
    ``p2p=False`` every call round-trips the head (the escape hatch,
    byte-for-byte the pre-two-level wire). With ``p2p=None`` the arm
    runs the DEFAULT config — no knob overrides at all — and widens
    the submit lane to the shapes that used to spill before the
    defaults flipped: retry-carrying tasks and ref-carrying args
    resident on the submitting node.

    The sustained-submit lane then arms a chaos ``sched_tick slow``
    plan (every head scheduler tick delayed by ``head_tick_delay_s``)
    and has a node-A task submit+get ``n_submit`` nested tasks: with
    local dispatch on, the node's LocalScheduler admits them without
    waiting out the slowed head tick.

    Returns {mode, p2p, n_calls, total, actor_seconds, calls_p2p,
    head_fallback, submit_seconds, local_dispatch, spillback,
    head_skip}. ``total`` must match between arms (equal results)."""
    import ray_tpu
    from ray_tpu import chaos
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.cluster_utils import Cluster

    overrides = ({} if p2p is None else
                 {"local_dispatch": bool(p2p), "actor_p2p": bool(p2p)})
    two_level_on = p2p is None or bool(p2p)
    ray_tpu.shutdown()
    c = Cluster(initialize_head=True,
                head_node_args=dict(
                    num_cpus=2, num_workers=2, scheduler="tensor",
                    _system_config=overrides))
    try:
        c.add_node(num_cpus=2, remote=True, resources={"a": 100.0})
        c.add_node(num_cpus=2, remote=True, resources={"b": 100.0})
        c.wait_for_nodes()
        w = worker_mod.get_worker()

        @ray_tpu.remote(resources={"b": 1.0})
        class _Acc:
            def __init__(self):
                self.total = 0

            def bump(self, x):
                self.total += x
                return self.total

        actor = _Acc.remote()
        ray_tpu.get(actor.bump.remote(0), timeout=60.0)  # placed + live

        @ray_tpu.remote(resources={"a": 1.0})
        def caller(h, n):
            import ray_tpu
            out = 0
            for _ in range(n):
                out = ray_tpu.get(h.bump.remote(1), timeout=60.0)
            return out

        t0 = time.perf_counter()
        total = ray_tpu.get(caller.remote(actor, n_calls),
                            timeout=300.0)
        actor_dt = time.perf_counter() - t0
        # sequenced p2p_done receipts ride the outbox; give the last
        # few a beat to land before reading the counters
        deadline = time.monotonic() + 10.0
        while (two_level_on and time.monotonic() < deadline
               and (w.two_level_stats["p2p"]
                    + w.two_level_stats["head_fallback"]) < n_calls - 1):
            time.sleep(0.05)
        stats = dict(w.two_level_stats)

        # the on/off A/B keeps the historical admissible shape (default
        # resources, no retries) so arms stay comparable release to
        # release; the default-config arm mixes in the shapes the
        # LocalScheduler used to spill and now admits — retry-carrying
        # tasks and ref-carrying args resident on the node
        @ray_tpu.remote(max_retries=0)
        def _nested_noop():
            return 1

        @ray_tpu.remote  # default task_max_retries: retry-carrying
        def _nested_retry():
            return 1

        @ray_tpu.remote(max_retries=0)
        def _nested_ref(blob):
            return 1 if blob else 0

        @ray_tpu.remote(resources={"a": 1.0})
        def submitter(n, mixed):
            import ray_tpu
            if not mixed:
                return sum(ray_tpu.get(
                    [_nested_noop.remote() for _ in range(n)],
                    timeout=120.0))
            # over inline_object_max_bytes -> sealed in node A's arena,
            # the shape the residency check admits locally
            data = ray_tpu.put(b"x" * (256 * 1024))
            refs = []
            for i in range(n):
                kind = i % 3
                if kind == 0:
                    refs.append(_nested_noop.remote())
                elif kind == 1:
                    refs.append(_nested_retry.remote())
                else:
                    refs.append(_nested_ref.remote(data))
            return sum(ray_tpu.get(refs, timeout=120.0))

        chaos.arm(chaos.FaultPlan(7))
        chaos.set_probability("sched_tick", 1.0,
                              delay_s=head_tick_delay_s)
        try:
            t0 = time.perf_counter()
            n_done = ray_tpu.get(
                submitter.remote(n_submit, p2p is None), timeout=300.0)
            submit_dt = time.perf_counter() - t0
        finally:
            chaos.disarm()
        stats_after = dict(w.two_level_stats)
        ld = int(stats_after["local_dispatch"])
        sb = int(stats_after["spillback"])
        return {
            "mode": "default" if p2p is None else
                    ("on" if p2p else "off"),
            "p2p": two_level_on,
            "n_calls": n_calls,
            "total": int(total),
            "actor_seconds": round(actor_dt, 3),
            "calls_p2p": int(stats["p2p"]),
            "head_fallback": int(stats["head_fallback"]),
            "n_submit": int(n_done),
            "submit_seconds": round(submit_dt, 3),
            "local_dispatch": ld,
            "spillback": sb,
            "head_skip": (round(ld / (ld + sb), 3) if ld + sb else None),
        }
    finally:
        c.shutdown()


def qos_ab(qos: bool, n_per_tenant: int = 30,
           n_submit: int = 16) -> Dict[str, Any]:
    """One arm of the QoS-plane A/B: a head + 1-remote-node cluster
    under a mixed two-tenant load (tenant "prod" at priority tier 1,
    weight 3; tenant "batch" at tier 0, weight 1), every task stamping
    its completion wall-clock so the driver gets honest per-task
    latency (submit -> finish) without serializing the gets.

    With ``qos=True`` the head drains by strict tier + weighted
    fair-share and resview frames carry the watermark (a queued tier-1
    backlog makes node daemons spill tier-0 nested submissions). With
    ``qos=False`` — the escape hatch, byte-for-byte the pre-QoS wire —
    the same submission mix runs FIFO. Preemption grace is set long so
    neither arm's latencies include kill/respawn time (the preemption
    path has its own tests).

    The head-skip lane runs DURING the load: a node-resident task
    submits ``n_submit`` nested no-ops, so the on-arm number shows
    what the watermark costs local admission under tier pressure.

    Returns {mode, n_tasks, seconds, tasks_per_sec, per-tier p50/p99
    ms, head_skip, local_dispatch, spillback, spillback_tier,
    preemptions, total}. ``total`` must match between arms."""
    import ray_tpu
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.cluster_utils import Cluster

    overrides: Dict[str, Any] = {"qos": bool(qos)}
    if qos:
        overrides["tenant_quotas"] = '{"prod": 3, "batch": 1}'
        overrides["preempt_grace_s"] = 300.0
    ray_tpu.shutdown()
    c = Cluster(initialize_head=True,
                head_node_args=dict(
                    num_cpus=2, num_workers=2, scheduler="tensor",
                    _system_config=overrides))
    try:
        c.add_node(num_cpus=2, remote=True, resources={"a": 100.0})
        c.wait_for_nodes()
        w = worker_mod.get_worker()

        @ray_tpu.remote(priority=1, tenant="prod")
        def prod_task(x):
            import time
            time.sleep(0.01)
            return (x, time.time())

        @ray_tpu.remote(tenant="batch")
        def batch_task(x):
            import time
            time.sleep(0.01)
            return (x, time.time())

        @ray_tpu.remote(max_retries=0)
        def _nested_noop():
            return 1

        @ray_tpu.remote(resources={"a": 1.0})
        def submitter(n):
            import ray_tpu
            return sum(ray_tpu.get(
                [_nested_noop.remote() for _ in range(n)],
                timeout=120.0))

        # one saturating burst, tiers interleaved adversarially
        # (every batch submitted before its prod peer), with the
        # head-skip submitter racing the same window
        refs, submits, tiers = [], [], []
        t0 = time.perf_counter()
        sub_ref = submitter.remote(n_submit)
        for i in range(n_per_tenant):
            submits.append(time.time())
            refs.append(batch_task.remote(i))
            tiers.append(0)
            submits.append(time.time())
            refs.append(prod_task.remote(i))
            tiers.append(1)
        out = ray_tpu.get(refs, timeout=300.0)
        wall = time.perf_counter() - t0
        n_done = ray_tpu.get(sub_ref, timeout=120.0)

        lat_ms: Dict[int, list] = {0: [], 1: []}
        total = 0
        for (x, end), t_sub, tier in zip(out, submits, tiers):
            total += x
            lat_ms[tier].append((end - t_sub) * 1000.0)

        def _pct(vals, q):
            vals = sorted(vals)
            return round(vals[min(len(vals) - 1,
                                  int(q * (len(vals) - 1)))], 2)

        stats = dict(w.two_level_stats)
        ld = int(stats.get("local_dispatch", 0))
        sb = int(stats.get("spillback", 0))
        plane = w.qos_plane
        return {
            "mode": "on" if qos else "off",
            "n_tasks": 2 * n_per_tenant,
            "seconds": round(wall, 3),
            "tasks_per_sec": round(2 * n_per_tenant / wall, 1),
            "tier0_p50_ms": _pct(lat_ms[0], 0.50),
            "tier0_p99_ms": _pct(lat_ms[0], 0.99),
            "tier1_p50_ms": _pct(lat_ms[1], 0.50),
            "tier1_p99_ms": _pct(lat_ms[1], 0.99),
            "n_submit": int(n_done),
            "local_dispatch": ld,
            "spillback": sb,
            "spillback_tier": int(stats.get("spillback:tier", 0)),
            "head_skip": (round(ld / (ld + sb), 3) if ld + sb else None),
            "preemptions": (plane.stats()["preemptions_total"]
                            if plane is not None else 0),
            "total": int(total),
        }
    finally:
        c.shutdown()


def rl_rollout_throughput(iters: int = 4) -> Dict[str, Any]:
    """IMPALA's async pipeline under load: env-steps/s streamed from
    runner actors through the object store into the V-trace learner
    (VERDICT r3 #3's 'rollout-throughput line'). Run with
    JAX_PLATFORMS=cpu — the policy is a toy MLP and stepping is host
    work; a tunneled accelerator would measure RTT, not the pipeline."""
    import ray_tpu
    from ray_tpu.rllib import IMPALAConfig

    ray_tpu.shutdown()
    ray_tpu.init(num_workers=8, scheduler="tensor")
    try:
        algo = IMPALAConfig(num_env_runners=4, num_envs_per_runner=8,
                            rollout_len=64, updates_per_iter=8,
                            seed=0).build()
        algo.train()  # warm the jits + pipeline
        steps = 0
        secs = 0.0
        returns = []
        for _ in range(iters):
            m = algo.train()
            steps += m["num_env_steps"]
            secs += m["num_env_steps"] / m["env_steps_per_sec"]
            if m["num_episodes"]:
                returns.append(m["episode_return_mean"])
        algo.stop()
    finally:
        ray_tpu.shutdown()
    return {
        "env_steps_per_sec": round(steps / max(secs, 1e-9), 1),
        "env_steps": steps,
        "episode_return_mean": (round(sum(returns) / len(returns), 1)
                                if returns else None),
    }


def data_pipeline_throughput(num_blocks: int = 100_000,
                             rows_per_block: int = 10,
                             num_workers: int = 8) -> Dict[str, Any]:
    """BASELINE config 3 through the REAL library: a map_batches pipeline
    over num_blocks blocks via the public ray_tpu.data API (streaming
    executor, backpressure, fused read+map), not a synthetic DAG."""
    import ray_tpu
    from ray_tpu import data

    ray_tpu.shutdown()
    ray_tpu.init(num_workers=num_workers, scheduler="tensor")
    try:
        n_rows = num_blocks * rows_per_block
        ds = data.range(n_rows, parallelism=num_blocks).map_batches(
            lambda b: [x * 2 for x in b])
        t0 = time.perf_counter()
        total = ds.count()
        dt = time.perf_counter() - t0
        assert total == n_rows, (total, n_rows)
        stats = ds.stats()
    finally:
        ray_tpu.shutdown()
    return {
        "num_blocks": num_blocks,
        "rows": n_rows,
        "seconds": dt,
        "blocks_per_sec": num_blocks / dt,
        "rows_per_sec": n_rows / dt,
        "stages": stats["stages"] if stats else None,
    }


def data_ingest_overlap(num_blocks: int = 96, rows_per_block: int = 50,
                        sleep_s: float = 0.025, consumers: int = 2,
                        num_workers: int = 8) -> Dict[str, Any]:
    """Streaming-split ingest vs. materialize-then-split, same pipeline
    in the same run. The map stage sleeps per block (a stand-in for
    real decode/transform work that releases the GIL, so thread
    workers overlap): the materialized baseline pays the WHOLE
    pipeline before its first batch; streaming_split hands consumers
    block 0 as soon as it finishes. Reports both time-to-first-batch
    values and the measured producer/consumer overlap fraction."""
    import threading

    import ray_tpu
    from ray_tpu import data
    from ray_tpu.data import block as blk

    ray_tpu.shutdown()
    ray_tpu.init(num_workers=num_workers, scheduler="tensor")
    try:
        def make_ds():
            def slow(b, _s=sleep_s):
                time.sleep(_s)
                return [x * 2 for x in b]

            return data.range(num_blocks * rows_per_block,
                              parallelism=num_blocks).map_batches(slow)

        # warm the pool + jit-free paths so neither side pays spin-up
        data.range(num_workers * 4, parallelism=num_workers * 4).count()

        # baseline: materialize, split by rank, first batch of shard 0
        t0 = time.perf_counter()
        refs = make_ds().materialize().block_refs
        ray_tpu.get(refs[0])
        ttfb_mat = time.perf_counter() - t0
        t_mat = time.perf_counter() - t0

        # streaming: identical pipeline through streaming_split
        shards = make_ds().streaming_split(consumers, equal=True)
        ttfb = [None] * consumers
        rows = [0] * consumers

        def drain(i: int, t_start: float):
            for b in shards[i].iter_batches():
                if ttfb[i] is None:
                    ttfb[i] = time.perf_counter() - t_start
                rows[i] += blk.block_rows(b)

        t1 = time.perf_counter()
        threads = [threading.Thread(target=drain, args=(i, t1))
                   for i in range(consumers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        t_stream = time.perf_counter() - t1
        split_stats = shards[0].stats()
        coord = shards[0].coordinator
        coord.shutdown()
        total_rows = num_blocks * rows_per_block
        assert sum(rows) == total_rows, (rows, total_rows)
        ttfb_stream = min(t for t in ttfb if t is not None)
    finally:
        ray_tpu.shutdown()
    return {
        "num_blocks": num_blocks,
        "rows": total_rows,
        "consumers": consumers,
        "ttfb_materialize_s": round(ttfb_mat, 4),
        "ttfb_streaming_s": round(ttfb_stream, 4),
        "ttfb_speedup": round(ttfb_mat / max(ttfb_stream, 1e-9), 1),
        "overlap_fraction": split_stats["overlap_fraction"],
        "materialize_total_s": round(t_mat, 4),
        "streaming_total_s": round(t_stream, 4),
        "streaming_blocks_per_sec": round(num_blocks / t_stream, 1),
        "backpressure_wait_s": split_stats["backpressure_wait_s"],
    }


def _arrow_data_bench(make_ds, warm_op, total_mb: int, num_blocks: int,
                      num_workers: int, arena_mult: int,
                      payload_mult: int,
                      worker_mode: str = "process",
                      best_of: int = 1) -> Dict[str, Any]:
    """Shared harness for the Arrow data-plane benchmarks: sized shm
    arena (the default 256 MB would thrash the spill tier and measure
    disk), a warm-up dataset to absorb worker spin-up and per-worker
    pyarrow imports (hundreds of ms each, serialized on small hosts),
    then a timed iter_batches pass with honest block-nbytes accounting.
    payload_mult: 2 counts in+out payload (map), 1 counts output only
    (exchange). best_of reruns the timed pass and keeps the fastest
    (page-cache warming on loaded single-CPU hosts dominates trial 0)."""
    import numpy as np
    import pyarrow as pa

    import ray_tpu
    from ray_tpu import data
    from ray_tpu.data import block as blk

    ray_tpu.shutdown()
    cfg = {"worker_mode": worker_mode}
    if worker_mode == "process":
        cfg["object_store_memory"] = (max(arena_mult * total_mb, 512)
                                      * 1024 * 1024)
    ray_tpu.init(num_workers=num_workers, scheduler="tensor",
                 _system_config=cfg)
    try:
        n_rows = total_mb * 1024 * 1024 // 8
        table = pa.table({"x": np.arange(n_rows, dtype=np.int64)})
        warm = pa.table({"x": np.arange(num_workers * 4, dtype=np.int64)})
        warm_op(data.from_arrow(warm, parallelism=num_workers * 4)).count()
        time.sleep(2.0)
        dt = None
        for _ in range(max(1, best_of)):
            ds = make_ds(data.from_arrow(table, parallelism=num_blocks))
            t0 = time.perf_counter()
            out_bytes = 0
            rows = 0
            for b in ds.iter_batches():
                out_bytes += blk.block_nbytes(b)
                rows += blk.block_rows(b)
            trial = time.perf_counter() - t0
            assert rows == n_rows, (rows, n_rows)
            dt = trial if dt is None else min(dt, trial)
    finally:
        ray_tpu.shutdown()
    return {
        "total_mb": round(payload_mult * out_bytes / 1e6, 1),
        "seconds": dt,
        "mb_per_sec": round(payload_mult * out_bytes / 1e6 / dt, 1),
        "num_blocks": num_blocks,
    }


def data_arrow_throughput(total_mb: int = 256, num_blocks: int = 64,
                          num_workers: int = 8) -> Dict[str, Any]:
    """Columnar path MB/s: Arrow blocks flow through a numpy-format
    map_batches in PROCESS workers (shm arena data plane; the sizes are
    real block nbytes, so MB/s is honest in+out payload throughput)."""
    def mapped(ds):
        return ds.map_batches(lambda cols: {"x": cols["x"] * 2},
                              batch_format="numpy")

    def warm(ds):
        return ds.map_batches(lambda cols: cols, batch_format="numpy")

    return _arrow_data_bench(mapped, warm, total_mb, num_blocks,
                             num_workers, arena_mult=4, payload_mult=2)


def data_shuffle_throughput(total_mb: int = 128, num_blocks: int = 16,
                            num_workers: int = 0) -> Dict[str, Any]:
    """Columnar all-to-all MB/s: random_shuffle over Arrow blocks.

    The exchange is two derived-permutation (Feistel PRP) gather
    stages running in the native C++ kernel (_native/exchange.cc) —
    rows never materialize, permutations are never stored. Runs in the
    framework's default thread mode (single-host shuffles have no
    reason to pay IPC) with workers sized to the host's cores; a
    best-of-3 absorbs page-cache warmup on loaded hosts."""
    import os

    def shuffled(ds, _seed=[0]):
        _seed[0] += 1
        return ds.random_shuffle(seed=_seed[0])

    nw = num_workers or max(2, min(8, os.cpu_count() or 2))
    return _arrow_data_bench(shuffled, shuffled, total_mb, num_blocks,
                             nw, arena_mult=6, payload_mult=1,
                             worker_mode="thread", best_of=3)


def data_join_throughput(total_mb: int = 64, num_blocks: int = 8,
                         num_workers: int = 0) -> Dict[str, Any]:
    """Columnar hash-join MB/s: key-partitioned exchange + Arrow hash
    join per reducer (data/_streaming.py join_exchange). Payload is
    the JOINED output's nbytes; thread mode + best-of-3 like the
    shuffle bench."""
    import os
    import time as _time

    import numpy as np
    import pyarrow as pa

    import ray_tpu
    from ray_tpu import data
    from ray_tpu.data import block as blk

    ray_tpu.shutdown()
    nw = num_workers or max(2, min(8, os.cpu_count() or 2))
    ray_tpu.init(num_workers=nw, scheduler="tensor",
                 _system_config={"worker_mode": "thread"})
    try:
        n_rows = total_mb * 1024 * 1024 // 16  # two int64 cols
        keys = np.arange(n_rows, dtype=np.int64)
        left_t = pa.table({"k": keys, "v": keys * 2})
        right_t = pa.table({"k": keys, "w": keys * 3})
        dt = None
        out_bytes = rows = 0
        for _ in range(3):
            left = data.from_arrow(left_t, parallelism=num_blocks)
            right = data.from_arrow(right_t, parallelism=num_blocks)
            t0 = _time.perf_counter()
            out_bytes = 0
            rows = 0
            for b in left.join(right, on="k")._execute():
                out_bytes += blk.block_nbytes(b)
                rows += blk.block_rows(b)
            trial = _time.perf_counter() - t0
            assert rows == n_rows, (rows, n_rows)
            dt = trial if dt is None else min(dt, trial)
    finally:
        ray_tpu.shutdown()
    return {
        "total_mb": round(out_bytes / 1e6, 1),
        "seconds": dt,
        "mb_per_sec": round(out_bytes / 1e6 / dt, 1),
        "num_blocks": num_blocks,
    }


def _flops_per_step(compiled, params, batch: int, seq: int) -> float:
    """XLA's own FLOP count for the compiled step; analytic fallback."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0))
        if flops > 0:
            return flops
    except Exception:
        pass
    # Analytic fallback: fwd+bwd ~ 6 * n_params * n_tokens.
    import jax

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    return 6.0 * n_params * batch * seq


def model_mfu(d_model: int = 2048, n_layers: int = 8, n_heads: int = 16,
              n_kv_heads: int = 8, d_ff: int = 5632,
              vocab_size: int = 32_768, seq_len: int = 2048,
              batch_size: int = 16, steps: int = 10,
              smoke: bool = False,
              remat_policy: str = "dots") -> Dict[str, Any]:
    """Flagship transformer train-step perf on the default device.

    Adaptive batch: halves on out-of-memory until the step fits. Returns
    step_ms, tokens_per_sec, flops_per_step, mfu, device info.
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import train_step as ts
    from ray_tpu.models.transformer import Transformer, TransformerConfig

    if smoke:
        sm = SMOKE_MODEL
        d_model, n_layers = sm["d_model"], sm["n_layers"]
        n_heads, n_kv_heads = sm["n_heads"], sm["n_kv_heads"]
        d_ff, vocab_size = sm["d_ff"], sm["vocab_size"]
        seq_len, batch_size, steps = (sm["seq_len"], sm["batch_size"],
                                      sm["steps"])

    dev = jax.devices()[0]
    cfg = TransformerConfig(vocab_size=vocab_size, d_model=d_model,
                            n_layers=n_layers, n_heads=n_heads,
                            n_kv_heads=n_kv_heads, d_ff=d_ff,
                            max_seq_len=seq_len,
                            remat=not smoke,
                            remat_policy=remat_policy)
    model = Transformer(cfg)
    optimizer = ts.make_optimizer()
    step_fn = ts.make_train_step(model, optimizer)

    last_err: Optional[BaseException] = None
    while batch_size >= 1:
        try:
            # random tokens: constant data (e.g. all-ones) is memorized
            # within the warmup+timing steps and collapses the loss to 0
            tokens = jax.random.randint(jax.random.PRNGKey(1),
                                        (batch_size, seq_len), 0, vocab_size,
                                        dtype=jnp.int32)
            params = jax.jit(
                lambda rng: model.init(rng, tokens)["params"])(
                    jax.random.PRNGKey(0))
            opt_state = jax.jit(optimizer.init)(params)
            step = jax.jit(step_fn, donate_argnums=(0, 1))
            lowered = step.lower(params, opt_state, {"tokens": tokens})
            compiled = lowered.compile()
            flops = _flops_per_step(compiled, params, batch_size, seq_len)
            # Warmup (first run may still include transfer/layout work).
            # NOTE sync discipline: block_until_ready is a no-op under
            # tunneled platforms (axon) — fetching a scalar is the only
            # reliable barrier, so time K chained steps between two
            # scalar fetches and amortize.
            params, opt_state, metrics = compiled(params, opt_state,
                                                  {"tokens": tokens})
            loss_host = float(jax.device_get(metrics["loss"]))
            t0 = time.perf_counter()
            for _ in range(steps):
                params, opt_state, metrics = compiled(
                    params, opt_state, {"tokens": tokens})
            loss_host = float(jax.device_get(metrics["loss"]))
            dt = (time.perf_counter() - t0) / steps
            break
        except Exception as e:  # XlaRuntimeError RESOURCE_EXHAUSTED etc.
            # Under a tunneled chip (axon) an HBM OOM surfaces as an opaque
            # INTERNAL remote_compile HTTP 500, not RESOURCE_EXHAUSTED.
            msg = str(e)
            oom_markers = ("RESOURCE_EXHAUSTED", "Out of memory",
                           "Ran out of memory", "remote_compile")
            if any(m in msg for m in oom_markers):
                last_err = e
                batch_size //= 2
                continue
            raise
    else:
        raise RuntimeError(f"model_mfu: could not fit batch: {last_err}")

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    peak = _peak_flops(dev.device_kind)
    # MFU convention: USEFUL model flops (6·N·D) over peak — remat
    # recompute does not count. The compiled program's own count (which
    # does include recompute) is the hardware utilization, reported as
    # hfu alongside.
    model_flops = 6.0 * n_params * batch_size * seq_len
    mfu = (model_flops / dt / peak) if peak else None
    hfu = (flops / dt / peak) if peak else None
    return {
        "device": dev.device_kind,
        "platform": dev.platform,
        "n_params": int(n_params),
        "batch_size": batch_size,
        "seq_len": seq_len,
        "step_ms": dt * 1e3,
        "tokens_per_sec": batch_size * seq_len / dt,
        "flops_per_step": flops,
        "model_flops_per_step": model_flops,
        "model_flops_per_sec": model_flops / dt,
        "hardware_flops_per_sec": flops / dt,
        "peak_flops": peak,
        "mfu": mfu,
        "hfu": hfu,
        "loss": loss_host,
    }


def model_time_sinks(top_k: int = 5, smoke: bool = False) -> list:
    """Top device-op time sinks of one flagship train step, from a
    jax.profiler trace (SURVEY §5 tracing note: xplane device
    timelines). Returns [{op, pct_of_device_time}] sorted descending —
    fusion.N names are XLA's own fusion labels."""
    import collections
    import glob
    import gzip
    import json
    import tempfile

    import jax
    import jax.numpy as jnp

    from ray_tpu.models import train_step as ts
    from ray_tpu.models.transformer import Transformer, TransformerConfig

    if smoke:
        cfg = TransformerConfig.tiny()
        batch, seq = 2, 128
    else:
        cfg = TransformerConfig(vocab_size=32_768, d_model=2048, n_layers=8,
                                n_heads=16, n_kv_heads=8, d_ff=5632,
                                max_seq_len=2048, remat=True,
                                remat_policy="dots")
        batch, seq = 8, 2048
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    params = jax.jit(lambda rng: model.init(rng, tokens)["params"])(
        jax.random.PRNGKey(0))
    optimizer = ts.make_optimizer()
    opt_state = jax.jit(optimizer.init)(params)
    step = jax.jit(ts.make_train_step(model, optimizer),
                   donate_argnums=(0, 1))
    compiled = step.lower(params, opt_state, {"tokens": tokens}).compile()
    params, opt_state, m = compiled(params, opt_state, {"tokens": tokens})
    float(jax.device_get(m["loss"]))
    n_steps = 2
    with tempfile.TemporaryDirectory() as td:
        with jax.profiler.trace(td):
            for _ in range(n_steps):
                params, opt_state, m = compiled(params, opt_state,
                                                {"tokens": tokens})
            float(jax.device_get(m["loss"]))
        traces = sorted(glob.glob(f"{td}/**/*.trace.json.gz",
                                  recursive=True))
        if not traces:
            return []
        events = json.loads(gzip.open(traces[-1]).read())["traceEvents"]
    # restrict to DEVICE lanes via process metadata (host runtime spans
    # like ExecuteCompiled would otherwise pollute ranking + total)
    device_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = (e.get("args") or {}).get("name", "")
            if "TPU" in pname or "device" in pname.lower():
                device_pids.add(e.get("pid"))
    dur: collections.Counter = collections.Counter()
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        name = e.get("name", "")
        # belt & braces when no metadata exists: python-host spans carry
        # $file:line names, jit_* is the whole program, ints are steps
        if name.startswith(("$", "jit_", "np.")) or name.isdigit():
            continue
        dur[name] += e["dur"]
    # the tunnel-merged trace duplicates device lanes, so absolute
    # durations overcount — report each op's SHARE of summed device
    # time (the ranking and proportions are what the trace is for)
    total = sum(dur.values()) or 1
    return [{"op": name, "pct_of_device_time": round(100.0 * d / total, 1)}
            for name, d in dur.most_common(top_k)]


def llm_decode_throughput(smoke: bool = False,
                          batch_slots: Optional[int] = None) -> dict:
    """Paged-attention decode tokens/s on the attached device
    (models/inference.py engine, full continuous batch). The analog of
    the reference serving stack's decode-throughput benchmark.

    batch_slots overrides the continuous-batch slot count (the bench
    sweeps 32/64/128 when budget allows: decode matmuls scale
    near-linearly with slots on the v5e — 32→10.2k, 64→14.9k,
    128→19.2k tok/s measured at 127M params in round 4)."""
    import time

    import jax
    import jax.numpy as jnp

    from ray_tpu.models.inference import InferenceConfig, InferenceEngine
    from ray_tpu.models.transformer import Transformer, TransformerConfig

    if smoke:
        sd = SMOKE_DECODE
        mcfg = TransformerConfig(
            vocab_size=sd["vocab_size"], d_model=sd["d_model"],
            n_layers=sd["n_layers"], n_heads=sd["n_heads"],
            n_kv_heads=sd["n_kv_heads"], d_ff=sd["d_ff"],
            max_seq_len=sd["max_seq_len"])
        batch, new_tokens, pages = (sd["batch"], sd["new_tokens"],
                                    sd["pages"])
    else:
        # serving-shaped model: head_dim 128 keeps the Pallas kernel on
        # full-width lanes. 64 continuous-batch slots x 128 new tokens:
        # the r3 config (32x64) left the MXU under-fed — the decode
        # matmuls scale near-linearly to 64 slots on this chip
        # (10.2k -> 17.9k tok/s measured) and longer decodes amortize
        # the per-burst host work
        mcfg = TransformerConfig(vocab_size=32000, d_model=1024,
                                 n_layers=8, n_heads=8, n_kv_heads=4,
                                 d_ff=2816, max_seq_len=2048)
        batch, new_tokens, pages = 64, 128, 1024
    if batch_slots is not None:
        batch = batch_slots
        pages = max(pages, batch * 16)
    model = Transformer(mcfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    icfg = InferenceConfig(batch_size=batch, page_size=16,
                           max_pages_per_seq=16, num_pages=pages,
                           prefill_buckets=(16,), max_new_tokens=new_tokens)
    engine = InferenceEngine(params, mcfg, icfg)
    try:
        # warm compiles with the SAME admission/chunk pattern as the
        # timed run (the batched prefill specializes on group size, the
        # decode programs on chunk size)
        warm = [engine.submit([i + 1] * 4, new_tokens)
                for i in range(batch)]
        for f in warm:
            f.result(timeout=900)
        t0 = time.perf_counter()
        futs = [engine.submit([i + 1] * 4, new_tokens)
                for i in range(batch)]
        total = sum(len(f.result(timeout=600)) for f in futs)
        dt = time.perf_counter() - t0
    finally:
        engine.shutdown()
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    return {
        "tokens_per_sec": total / dt,
        "batch_slots": batch,
        "new_tokens": new_tokens,
        "n_params": int(n_params),
        "seconds": dt,
    }


def serving_ab(disagg: bool, sessions: int = 8, turns: int = 2,
               max_new: int = 48) -> Dict[str, Any]:
    """One arm of the serving-plane A/B: mono (N LLMDeployment
    replicas, prefill and decode share each replica's continuous
    batch) vs disaggregated (1 prefill + 1 decode replica — the same
    TWO replicas of hardware) under a mixed interactive load:
    ``sessions`` concurrent sessions, each streaming ``turns`` turns
    of ``max_new`` tokens, follow-up turns reusing the session id so
    the disaggregated arm exercises cache-affinity routing.

    Engine batches are deliberately SMALLER than the offered load
    (batch_size=2 per engine, sessions > total slots): in the mono
    arm a new prompt's first token waits for a continuous-batch slot
    behind whole ongoing decodes, while the disaggregated arm streams
    the first token straight off the prefill handoff — the TTFT
    contrast under saturation is exactly what the split buys.

    TTFT is measured CLIENT-side (first non-empty frame) so both arms
    are scored by the same clock. CPU-host caveat: both arms share
    one host's cores, so tokens/s differences are scheduling effects,
    not accelerator effects; the TTFT ordering is the honest signal.

    Returns {mode, sessions, turns, max_new, replicas, ttft_p50_ms/
    p95/p99, tokens_per_sec, tokens_per_sec_per_replica, total_tokens,
    seconds, affinity_hit_rate, kv_bytes, sheds}."""
    import threading

    import jax
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.models.inference import InferenceConfig
    from ray_tpu.models.transformer import Transformer, TransformerConfig
    from ray_tpu.serve import core
    from ray_tpu.serve.llm import build_llm_app, run_disagg_llm

    mcfg = TransformerConfig(vocab_size=128, d_model=32, n_layers=2,
                             n_heads=2, n_kv_heads=2, d_ff=64,
                             max_seq_len=128)
    icfg = InferenceConfig(batch_size=2, page_size=4,
                           max_pages_per_seq=16, num_pages=64,
                           prefill_buckets=(16,),
                           max_new_tokens=max_new, decode_chunk=1)
    model = Transformer(mcfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    replicas = 2  # both arms: two engine-hosting replicas
    ray_tpu.init(num_workers=2)
    try:
        if disagg:
            handle = run_disagg_llm(params, mcfg, icfg,
                                    prefill_replicas=1,
                                    decode_replicas=1)

            def frames(prompt, session):
                return handle.stream_frames(prompt, max_new,
                                            session_id=session)
        else:
            h = serve.run(build_llm_app(params, mcfg, icfg,
                                        num_replicas=replicas))
            st = h._state()

            def frames(prompt, session):
                return core._sticky_stream_frames(st, prompt, max_new,
                                                  start_timeout=300.0)

        # warm the compile caches with the run's own shapes (prefill
        # bucket, decode chunk, KV import) before the timed window;
        # one concurrent stream per replica reaches both mono engines
        warm = [threading.Thread(
            target=lambda i=i: [None for _ in frames([i + 1] * 4, None)])
            for i in range(replicas)]
        for t in warm:
            t.start()
        for t in warm:
            t.join()
        core.metrics.reset()

        results: list = []
        lock = threading.Lock()

        def run_session(i: int) -> None:
            session = f"bench-s{i}"
            prompt = [(i * 7 + j) % 100 + 1 for j in range(6)]
            for _turn in range(turns):
                t0 = time.perf_counter()
                ttft = None
                n = 0
                for fr in frames(prompt, session):
                    toks = fr.get("tokens") or ()
                    if toks and ttft is None:
                        ttft = time.perf_counter() - t0
                    n += len(toks)
                with lock:
                    results.append((ttft, n))

        threads = [threading.Thread(target=run_session, args=(i,),
                                    daemon=True)
                   for i in range(sessions)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start

        ttfts = sorted(t for t, _ in results if t is not None)

        def _pct(q: float) -> Optional[float]:
            if not ttfts:
                return None
            return ttfts[min(len(ttfts) - 1, int(q * len(ttfts)))]

        total_tokens = sum(n for _, n in results)
        snap = core.metrics.snapshot()
        aff = snap["affinity_hit"] + snap["affinity_miss"]
        return {
            "mode": "disagg" if disagg else "mono",
            "sessions": sessions,
            "turns": turns,
            "max_new": max_new,
            "replicas": replicas,
            "n_streams": len(results),
            "ttft_p50_ms": round(_pct(0.50) * 1e3, 2) if ttfts else None,
            "ttft_p95_ms": round(_pct(0.95) * 1e3, 2) if ttfts else None,
            "ttft_p99_ms": round(_pct(0.99) * 1e3, 2) if ttfts else None,
            "tokens_per_sec": round(total_tokens / wall, 1),
            "tokens_per_sec_per_replica":
                round(total_tokens / wall / replicas, 1),
            "total_tokens": total_tokens,
            "seconds": round(wall, 3),
            "affinity_hit_rate": (round(snap["affinity_hit"] / aff, 3)
                                  if aff else None),
            "kv_bytes": snap["kv_bytes"],
            "sheds": snap["admission_shed"],
        }
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
