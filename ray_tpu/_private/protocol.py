"""Wire-protocol versioning for every control-plane handshake.

Role of the reference's protobuf IDL version discipline (ray:
src/ray/protobuf/ — schema evolution gives version-skew safety): this
runtime speaks framed pickled tuples, so skew safety comes from an
explicit protocol version carried in EVERY hello — head registration
(daemons, clients), intra-node worker attach, and the peer object
plane. A listener that sees a different version (or a pre-versioned
tuple) rejects the dial with a clear error instead of failing later on
a shape mismatch deep inside a message handler.

The version bumps whenever any framed-tuple message shape changes.
"""

from __future__ import annotations

from typing import Optional, Tuple

# v1: unversioned round-3 wire; v2: versioned tuple hellos (round 4);
# v3: proto3 Hello/Reject envelopes (round 5). v3 acceptors parse v2
# tuple hellos and reject them with a clear error; a v3 dialer against
# a v2 acceptor is a ONE-WAY break — the old binary drops the bytes
# hello silently (its parser predates proto), so upgrade heads before
# nodes/clients.
PROTOCOL_VERSION = 3


def make_hello(*fields) -> tuple:
    """A versioned hello: ("hello", PROTOCOL_VERSION, *fields)."""
    return ("hello", PROTOCOL_VERSION) + fields


def split_hello(hello) -> Tuple[Optional[int], tuple]:
    """(version, fields) of a received hello.

    Version is None for malformed or pre-versioned senders (their
    first field is never an int)."""
    if not (isinstance(hello, tuple) and hello and hello[0] == "hello"):
        return None, ()
    if (len(hello) == 3 and hello[2] in ("task", "ctrl")
            and isinstance(hello[1], int)):
        # legacy UNVERSIONED intra-node worker hello was
        # ("hello", <int worker_num>, kind) — the int is a worker
        # number, not a version; without this case the dialer gets a
        # baffling "peer sent protocol v<worker_num>" (or a silent
        # accept when worker_num happens to equal PROTOCOL_VERSION)
        return None, tuple(hello[1:])
    if len(hello) >= 2 and isinstance(hello[1], int) \
            and not isinstance(hello[1], bool):
        return hello[1], tuple(hello[2:])
    return None, tuple(hello[1:])


def mismatch_error(listener: str, version: Optional[int]) -> tuple:
    """The rejection reply a listener sends before closing the dial."""
    got = "an unversioned (pre-v2) hello" if version is None \
        else f"protocol v{version}"
    return ("error",
            f"protocol version mismatch: {listener} speaks "
            f"v{PROTOCOL_VERSION}, peer sent {got}; run the same "
            "ray_tpu version on every node/client")


# ----------------------------------------------------------------------
# proto3 envelope (reference: src/ray/protobuf/ — the schema'd wire).
# wire.proto defines Hello/Reject; wire_pb2.py is the checked-in
# codegen. The handshake layer speaks proto BYTES; legacy tuple hellos
# still parse (split_any_hello) so mixed versions fail with a clear
# rejection instead of a shape error.
# ----------------------------------------------------------------------

def make_wire_hello(role: str, *fields) -> bytes:
    """Schema'd hello bytes (ray_tpu.wire.Hello). The caller STATES the
    role — "worker" (fields: num, kind), "client" (fields: client_id),
    or any daemon role/token (fields ride ``payload`` pickled, the
    documented single-language extras behind a language-neutral
    envelope). Version + role + the scalar worker/client fields are
    proto-parseable by any language."""
    import pickle as _pickle

    from ray_tpu._private import wire_pb2

    hello = wire_pb2.Hello(protocol_version=PROTOCOL_VERSION,
                           role=role)
    if role == "worker":
        num, kind = fields
        hello.worker_num = num
        hello.kind = kind
    elif role == "client":
        (hello.client_id,) = fields
    elif fields:
        hello.payload = _pickle.dumps(tuple(fields))
    return hello.SerializeToString()


def split_any_hello(msg) -> Tuple[Optional[int], tuple]:
    """(version, legacy-shaped fields) from a proto-bytes hello OR a
    legacy tuple — every acceptor's downstream destructuring sees the
    same field tuples either way."""
    if isinstance(msg, (bytes, bytearray)):
        import pickle as _pickle

        from ray_tpu._private import wire_pb2

        hello = wire_pb2.Hello()
        try:
            hello.ParseFromString(bytes(msg))
        except Exception:  # noqa: BLE001 (DecodeError + runtime variants)
            return None, ()
        if not hello.role:
            return None, ()
        try:
            if hello.role == "worker":
                fields: tuple = (hello.worker_num, hello.kind)
            elif hello.role == "client":
                fields = ("client", hello.client_id)
            elif hello.payload:
                fields = (hello.role,) + tuple(
                    _pickle.loads(hello.payload))
            else:
                fields = (hello.role,)
        except Exception:  # noqa: BLE001 (torn payload)
            return None, ()
        return hello.protocol_version, fields
    return split_hello(msg)


def proto_reject(reason: str) -> bytes:
    """Schema'd rejection bytes: ray_tpu.wire.Reject."""
    from ray_tpu._private import wire_pb2

    return wire_pb2.Reject(reason=reason,
                           speaker_version=PROTOCOL_VERSION
                           ).SerializeToString()
