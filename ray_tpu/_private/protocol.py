"""Wire-protocol versioning for every control-plane handshake.

Role of the reference's protobuf IDL version discipline (ray:
src/ray/protobuf/ — schema evolution gives version-skew safety): this
runtime speaks framed pickled tuples, so skew safety comes from an
explicit protocol version carried in EVERY hello — head registration
(daemons, clients), intra-node worker attach, and the peer object
plane. A listener that sees a different version (or a pre-versioned
tuple) rejects the dial with a clear error instead of failing later on
a shape mismatch deep inside a message handler.

The version bumps whenever any framed-tuple message shape changes.
"""

from __future__ import annotations

from typing import Optional, Tuple

PROTOCOL_VERSION = 2  # v1 was the unversioned round-3 wire


def make_hello(*fields) -> tuple:
    """A versioned hello: ("hello", PROTOCOL_VERSION, *fields)."""
    return ("hello", PROTOCOL_VERSION) + fields


def split_hello(hello) -> Tuple[Optional[int], tuple]:
    """(version, fields) of a received hello.

    Version is None for malformed or pre-versioned senders (their
    first field is never an int)."""
    if not (isinstance(hello, tuple) and hello and hello[0] == "hello"):
        return None, ()
    if (len(hello) == 3 and hello[2] in ("task", "ctrl")
            and isinstance(hello[1], int)):
        # legacy UNVERSIONED intra-node worker hello was
        # ("hello", <int worker_num>, kind) — the int is a worker
        # number, not a version; without this case the dialer gets a
        # baffling "peer sent protocol v<worker_num>" (or a silent
        # accept when worker_num happens to equal PROTOCOL_VERSION)
        return None, tuple(hello[1:])
    if len(hello) >= 2 and isinstance(hello[1], int) \
            and not isinstance(hello[1], bool):
        return hello[1], tuple(hello[2:])
    return None, tuple(hello[1:])


def mismatch_error(listener: str, version: Optional[int]) -> tuple:
    """The rejection reply a listener sends before closing the dial."""
    got = "an unversioned (pre-v2) hello" if version is None \
        else f"protocol v{version}"
    return ("error",
            f"protocol version mismatch: {listener} speaks "
            f"v{PROTOCOL_VERSION}, peer sent {got}; run the same "
            "ray_tpu version on every node/client")
