"""Wire-protocol versioning for every control-plane handshake.

Role of the reference's protobuf IDL version discipline (ray:
src/ray/protobuf/ — schema evolution gives version-skew safety): this
runtime speaks framed pickled tuples, so skew safety comes from an
explicit protocol version carried in EVERY hello — head registration
(daemons, clients), intra-node worker attach, and the peer object
plane. A listener that sees a different version (or a pre-versioned
tuple) rejects the dial with a clear error instead of failing later on
a shape mismatch deep inside a message handler.

The version bumps whenever any framed-tuple message shape changes.
"""

from __future__ import annotations

from typing import Optional, Tuple

PROTOCOL_VERSION = 2  # v1 was the unversioned round-3 wire


def make_hello(*fields) -> tuple:
    """A versioned hello: ("hello", PROTOCOL_VERSION, *fields)."""
    return ("hello", PROTOCOL_VERSION) + fields


def split_hello(hello) -> Tuple[Optional[int], tuple]:
    """(version, fields) of a received hello.

    Version is None for malformed or pre-versioned senders (their
    first field is never an int)."""
    if not (isinstance(hello, tuple) and hello and hello[0] == "hello"):
        return None, ()
    if (len(hello) == 3 and hello[2] in ("task", "ctrl")
            and isinstance(hello[1], int)):
        # legacy UNVERSIONED intra-node worker hello was
        # ("hello", <int worker_num>, kind) — the int is a worker
        # number, not a version; without this case the dialer gets a
        # baffling "peer sent protocol v<worker_num>" (or a silent
        # accept when worker_num happens to equal PROTOCOL_VERSION)
        return None, tuple(hello[1:])
    if len(hello) >= 2 and isinstance(hello[1], int) \
            and not isinstance(hello[1], bool):
        return hello[1], tuple(hello[2:])
    return None, tuple(hello[1:])


def mismatch_error(listener: str, version: Optional[int]) -> tuple:
    """The rejection reply a listener sends before closing the dial."""
    got = "an unversioned (pre-v2) hello" if version is None \
        else f"protocol v{version}"
    return ("error",
            f"protocol version mismatch: {listener} speaks "
            f"v{PROTOCOL_VERSION}, peer sent {got}; run the same "
            "ray_tpu version on every node/client")


# ----------------------------------------------------------------------
# proto3 envelope (reference: src/ray/protobuf/ — the schema'd wire).
# wire.proto defines Hello/Reject; wire_pb2.py is the checked-in
# codegen. The handshake layer speaks proto BYTES; legacy tuple hellos
# still parse (split_any_hello) so mixed versions fail with a clear
# rejection instead of a shape error.
# ----------------------------------------------------------------------

def make_proto_hello(role: str, *, worker_num: int = 0,
                     kind: str = "", client_id: str = "",
                     payload: bytes = b"") -> bytes:
    """Schema'd hello bytes: ray_tpu.wire.Hello."""
    from ray_tpu._private import wire_pb2

    return wire_pb2.Hello(
        protocol_version=PROTOCOL_VERSION, role=role,
        worker_num=worker_num, kind=kind, client_id=client_id,
        payload=payload).SerializeToString()


def split_any_hello(msg) -> Tuple[Optional[int], tuple]:
    """(version, fields) from a proto-bytes hello OR a legacy tuple.

    Proto hellos yield fields (role, worker_num, kind, client_id,
    payload); tuple hellos keep their tuple fields."""
    if isinstance(msg, (bytes, bytearray)):
        from ray_tpu._private import wire_pb2

        hello = wire_pb2.Hello()
        try:
            hello.ParseFromString(bytes(msg))
        except Exception:  # noqa: BLE001 (DecodeError + runtime variants)
            return None, ()
        if not hello.role:
            return None, ()
        return hello.protocol_version, (hello.role, hello.worker_num,
                                        hello.kind, hello.client_id,
                                        hello.payload)
    return split_hello(msg)


def proto_reject(reason: str) -> bytes:
    """Schema'd rejection bytes: ray_tpu.wire.Reject."""
    from ray_tpu._private import wire_pb2

    return wire_pb2.Reject(reason=reason,
                           speaker_version=PROTOCOL_VERSION
                           ).SerializeToString()
