"""Log plane: per-process capture files + helpers shared by every layer.

The reference framework treats worker logs as a first-class subsystem
(`python/ray/_private/log_monitor.py`, `ray logs`): every worker
redirects stdout/stderr into per-session files, a monitor tails them
and re-emits on the driver, and the state API / CLI / dashboard read
the same files. This module is the shared substrate for all of that:

- session log directory resolution (`/tmp/ray_tpu/session_*/logs`,
  honoring the ``log_dir`` config knob — erroring loudly if the knob
  is set but the directory cannot be created);
- fd-level stdout/stderr redirection for exec'd processes (``dup2``,
  line-buffered, size-rotated) so ordinary prints AND interpreter
  crash tracebacks land in the files;
- safe file enumeration / tail reads used by ``util.state.list_logs``
  / ``get_log``, the ``python -m ray_tpu logs`` CLI and the dashboard
  (filenames are validated so a query can never escape the log dir).

Everything here is stdlib-only and import-light: worker processes and
node daemons import it before the heavy runtime comes up.
"""

from __future__ import annotations

import io
import os
import re
import sys
import threading
import time
from typing import Dict, List, Optional

# Env vars the spawners set for exec'd children (worker processes and
# node daemons). Paths are full file paths; rotation knobs ride along
# so children honor the head's config without importing it pre-init.
ENV_LOG_OUT = "RAY_TPU_LOG_OUT"
ENV_LOG_ERR = "RAY_TPU_LOG_ERR"
ENV_LOG_ROTATE_BYTES = "RAY_TPU_LOG_ROTATE_BYTES"
ENV_LOG_ROTATE_BACKUPS = "RAY_TPU_LOG_ROTATE_BACKUPS"

_SESSION_DIR_RE = re.compile(r"^session_\d+_\d+$")
_FILENAME_RE = re.compile(r"^[A-Za-z0-9._-]+$")

# The driver-side session log dir for this process, once resolved.
_session_log_dir: Optional[str] = None
_session_lock = threading.Lock()


# ---------------------------------------------------------------------------
# session directory
# ---------------------------------------------------------------------------
def resolve_session_log_dir(log_dir: str = "",
                            root: str = "/tmp/ray_tpu") -> str:
    """Create and return the session log directory.

    ``log_dir`` (the config knob) wins when non-empty; otherwise a
    fresh ``<root>/session_<epoch_ms>_<pid>/logs`` is created. A knob
    that is set but uncreatable raises RuntimeError instead of
    silently falling back — a configured log dir that quietly ends up
    elsewhere is worse than a crash at init.
    """
    if log_dir:
        try:
            os.makedirs(log_dir, exist_ok=True)
            probe = os.path.join(log_dir, ".probe")
            with open(probe, "w"):
                pass
            os.unlink(probe)
        except OSError as e:
            raise RuntimeError(
                f"log_dir={log_dir!r} is set but not creatable/writable: "
                f"{e}") from e
        return os.path.abspath(log_dir)
    path = os.path.join(root, f"session_{int(time.time() * 1000)}_"
                              f"{os.getpid()}", "logs")
    os.makedirs(path, exist_ok=True)
    return os.path.abspath(path)


def set_session_log_dir(path: Optional[str]) -> None:
    global _session_log_dir
    with _session_lock:
        _session_log_dir = path


def get_session_log_dir() -> Optional[str]:
    with _session_lock:
        return _session_log_dir


def latest_session_log_dir(root: str = "/tmp/ray_tpu") -> Optional[str]:
    """Newest ``session_*/logs`` dir under ``root`` (postmortem CLI)."""
    try:
        names = [n for n in os.listdir(root) if _SESSION_DIR_RE.match(n)]
    except OSError:
        return None
    best = None
    best_mtime = -1.0
    for n in names:
        d = os.path.join(root, n, "logs")
        try:
            m = os.stat(d).st_mtime
        except OSError:
            continue
        if m > best_mtime:
            best, best_mtime = d, m
    return best


# ---------------------------------------------------------------------------
# fd redirection with size rotation (exec'd children)
# ---------------------------------------------------------------------------
class _RotatingFdStream(io.TextIOBase):
    """Line-buffered text stream over a real fd, rotated by size.

    The fd is also ``dup2``'d over the std fd (1 or 2), so writes that
    bypass Python — C extensions, the interpreter's own crash
    traceback — land in the same file. Rotation renames the file
    chain (``f`` -> ``f.1`` -> ... -> ``f.N``), reopens ``f`` and
    re-``dup2``s so the std fd follows the fresh file too.
    """

    def __init__(self, path: str, std_fd: int, rotate_bytes: int,
                 backups: int):
        self._path = path
        self._std_fd = std_fd
        self._rotate_bytes = max(0, int(rotate_bytes))
        self._backups = max(0, int(backups))
        self._lock = threading.Lock()
        self._fd = self._open()
        os.dup2(self._fd, std_fd)

    def _open(self) -> int:
        return os.open(self._path,
                       os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)

    def _maybe_rotate(self) -> None:
        if not self._rotate_bytes:
            return
        try:
            size = os.fstat(self._fd).st_size
        except OSError:
            return
        if size < self._rotate_bytes:
            return
        try:
            if self._backups:
                for i in range(self._backups - 1, 0, -1):
                    src = f"{self._path}.{i}"
                    if os.path.exists(src):
                        os.replace(src, f"{self._path}.{i + 1}")
                os.replace(self._path, f"{self._path}.1")
            else:
                os.unlink(self._path)
        except OSError:
            return
        old = self._fd
        self._fd = self._open()
        os.dup2(self._fd, self._std_fd)
        try:
            os.close(old)
        except OSError:
            pass

    # -- TextIOBase interface ------------------------------------------
    def writable(self) -> bool:  # pragma: no cover - trivial
        return True

    def write(self, s: str) -> int:
        if not isinstance(s, str):
            s = str(s)
        data = s.encode("utf-8", "replace")
        with self._lock:
            self._maybe_rotate()
            os.write(self._fd, data)
        return len(s)

    def flush(self) -> None:
        pass  # os.write is unbuffered

    def fileno(self) -> int:
        return self._fd

    @property
    def name(self) -> str:  # pragma: no cover - introspection only
        return self._path


def redirect_stdio(out_path: str, err_path: str, rotate_bytes: int = 0,
                   backups: int = 0) -> None:
    """Redirect this process's stdout/stderr into capture files.

    Installs ``_RotatingFdStream`` objects as ``sys.stdout`` /
    ``sys.stderr`` and ``dup2``s the file fds over 1 and 2, so both
    Python-level prints and raw-fd writes (including the interpreter's
    fatal tracebacks) are captured, line-buffered.
    """
    sys.stdout = _RotatingFdStream(out_path, 1, rotate_bytes, backups)
    sys.stderr = _RotatingFdStream(err_path, 2, rotate_bytes, backups)


def redirect_stdio_from_env(environ=os.environ) -> bool:
    """Install redirection if the spawner requested it via env vars.

    Returns True if redirection was installed. Called at the very top
    of exec'd entrypoints (worker_process, node_daemon) so every later
    byte — including import-time failures — is captured.
    """
    out = environ.get(ENV_LOG_OUT)
    err = environ.get(ENV_LOG_ERR)
    if not out or not err:
        return False
    try:
        rotate = int(environ.get(ENV_LOG_ROTATE_BYTES, "0") or 0)
        backups = int(environ.get(ENV_LOG_ROTATE_BACKUPS, "0") or 0)
    except ValueError:
        rotate, backups = 0, 0
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    redirect_stdio(out, err, rotate, backups)
    return True


def child_log_env(log_dir: Optional[str], stem: str, rotate_bytes: int,
                  backups: int) -> Dict[str, str]:
    """Env-var block a spawner merges into a child's environment."""
    if not log_dir:
        return {}
    return {
        ENV_LOG_OUT: os.path.join(log_dir, f"{stem}.out"),
        ENV_LOG_ERR: os.path.join(log_dir, f"{stem}.err"),
        ENV_LOG_ROTATE_BYTES: str(int(rotate_bytes)),
        ENV_LOG_ROTATE_BACKUPS: str(int(backups)),
    }


# ---------------------------------------------------------------------------
# file enumeration / reads (state verbs, CLI, dashboard)
# ---------------------------------------------------------------------------
def validate_filename(filename: str) -> str:
    """Reject anything that could escape the log directory."""
    if not filename or not _FILENAME_RE.match(filename) \
            or filename in (".", ".."):
        raise ValueError(f"invalid log filename: {filename!r}")
    return filename


def list_log_files(log_dir: str) -> List[Dict[str, object]]:
    """Enumerate capture files as {filename, size_bytes, mtime} rows."""
    rows: List[Dict[str, object]] = []
    try:
        names = sorted(os.listdir(log_dir))
    except OSError:
        return rows
    for n in names:
        p = os.path.join(log_dir, n)
        try:
            st = os.stat(p)
        except OSError:
            continue
        if not os.path.isfile(p):
            continue
        rows.append({"filename": n, "size_bytes": st.st_size,
                     "mtime": st.st_mtime})
    return rows


def read_log(log_dir: str, filename: str,
             tail: Optional[int] = None) -> str:
    """Read a capture file (optionally only its last ``tail`` lines).

    ``filename`` is validated and the resolved path must stay inside
    ``log_dir`` — state verbs and the dashboard call this with
    user-supplied names.
    """
    validate_filename(filename)
    base = os.path.realpath(log_dir)
    path = os.path.realpath(os.path.join(base, filename))
    if os.path.dirname(path) != base:
        raise ValueError(f"log filename escapes log dir: {filename!r}")
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no such log file: {filename!r} in {log_dir}")
    if tail is None:
        with open(path, "r", errors="replace") as f:
            return f.read()
    return "\n".join(tail_file(path, int(tail)))


def tail_file(path: str, n: int, max_bytes: int = 1 << 20) -> List[str]:
    """Last ``n`` lines of ``path`` (reads at most ``max_bytes``)."""
    if n <= 0:
        return []
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            data = f.read()
    except OSError:
        return []
    text = data.decode("utf-8", "replace")
    lines = text.splitlines()
    return lines[-n:]


def err_tail_message(err_path: Optional[str], n: int = 20) -> str:
    """Formatted ``.err`` tail appended to WorkerCrashedError messages.

    Empty string when there is nothing useful to show — callers append
    unconditionally.
    """
    if not err_path:
        return ""
    lines = tail_file(err_path, n)
    if not lines:
        return ""
    body = "\n".join(f"  {ln}" for ln in lines)
    return (f"\n--- last {len(lines)} lines of "
            f"{os.path.basename(err_path)} ---\n{body}")
