"""Cluster flight recorder: continuous profiling + utilization plane.

The fourth leg of the observability substrate.  Logs capture output,
the task event plane records per-attempt lifecycles, the trace plane
links them causally — this module answers the two questions those
planes keep raising: *what is the CPU actually doing* and *how loaded
is each node over time*.

Two producers, one head-side aggregator:

- :class:`StackSampler` — a daemon thread in every process worker (and
  on the head) walks ``sys._current_frames()`` at ``profile_hz``,
  collapses each stack into a folded ``a;b;c`` string tagged with the
  currently-executing task (ambient context the task-event/trace
  planes already maintain), and hands bounded count batches to a flush
  callback.  Worker batches ride the existing owner pipe as a
  ``("prof", payload)`` message — for daemon-spawned workers that
  message is forwarded as a ``("w", ...)`` report and therefore rides
  the daemon outbox, so samples survive a head blackout + rejoin.
- :class:`ResourceSampler` — a per-node thread reading ``/proc/stat``
  /proc/meminfo`` (the ONE parser ``memory_monitor.host_memory`` also
  uses) plus caller-provided internal gauges (shm arena occupancy,
  control-ring traffic, scheduler queue depths) at
  ``utilization_interval_s``.  Daemons ship each sample as an
  outbox-riding ``("util", payload)`` report; the head records its own
  samples directly.

:class:`ProfilePlane` is the head-side consumer surface: a bounded
folded-stack count table (``profile_stacks_max``, oldest evicted) and
a bounded per-(node, series) time-series ring
(:class:`UtilizationRing`, ``utilization_ring`` points) with
fixed-interval downsampling; off-head timestamps are aligned onto the
head's axis via the same per-pool ``clock_offset`` the task event and
trace planes use.  Disabled contract mirrors the trace plane:
``profile_hz=0`` (the default) leaves ``worker.profile_plane`` as
``None``, no sampler threads exist anywhere, every producer hook is an
``is not None`` check, and the metric families render schema-stable
zeros.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private.analysis import runtime_sanitizer
from ray_tpu._private.analysis.runtime_checks import assert_holds

# ----------------------------------------------------------------------
# /proc parsers — the one shared implementation (memory_monitor's
# host_memory() delegates here; keep signatures/semantics stable)
# ----------------------------------------------------------------------

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def read_meminfo() -> Tuple[int, int]:
    """(used_bytes, total_bytes) for the host, from /proc/meminfo —
    used = MemTotal - MemAvailable (the kernel's own reclaimable-aware
    estimate).  Returns (0, 1) when /proc is unavailable (macOS CI),
    matching the historical memory_monitor fallback."""
    total = available = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    available = int(line.split()[1]) * 1024
                if total is not None and available is not None:
                    break
    except OSError:
        return (0, 1)
    if total is None or available is None:
        return (0, 1)
    return (total - available, total)


def read_self_rss() -> int:
    """Resident set size of THIS process in bytes (0 off-Linux)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return 0


def read_proc_stat() -> Optional[Tuple[int, int]]:
    """(busy_jiffies, total_jiffies) from the aggregate cpu line of
    /proc/stat, or None off-Linux.  busy excludes idle + iowait."""
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()
    except OSError:
        return None
    if not parts or parts[0] != "cpu":
        return None
    try:
        fields = [int(x) for x in parts[1:]]
    except ValueError:
        return None
    total = sum(fields)
    idle = fields[3] if len(fields) > 3 else 0
    iowait = fields[4] if len(fields) > 4 else 0
    return (total - idle - iowait, total)


class CpuPercent:
    """Stateful host CPU utilization from successive /proc/stat deltas.
    The first sample (no delta yet) reports 0.0."""

    def __init__(self) -> None:
        self._last = read_proc_stat()

    def sample(self) -> float:
        cur = read_proc_stat()
        last, self._last = self._last, cur
        if cur is None or last is None:
            return 0.0
        dt = cur[1] - last[1]
        if dt <= 0:
            return 0.0
        return round(100.0 * max(cur[0] - last[0], 0) / dt, 2)


# ----------------------------------------------------------------------
# stack folding + the sampling profiler thread
# ----------------------------------------------------------------------

_MAX_DEPTH = 64


def fold_stack(frame) -> str:
    """Collapse one frame chain into a root-first ``mod.func;...``
    folded-stack string (Brendan Gregg's collapsed format, one level
    per frame)."""
    out: List[str] = []
    while frame is not None and len(out) < _MAX_DEPTH:
        mod = frame.f_globals.get("__name__", "?")
        out.append(f"{mod}.{frame.f_code.co_name}")
        frame = frame.f_back
    out.reverse()
    return ";".join(out)


class StackSampler:
    """Continuous sampling profiler: one daemon thread walking
    ``sys._current_frames()`` at ``hz``.

    ``label_fn`` (worker mode) names the sample after the currently
    executing task; only the main thread — where tasks run — is
    sampled.  With ``all_threads=True`` (the head) every thread is
    sampled and labeled by its thread name.  Folded counts accumulate
    in a bounded buffer (overflow counted, not kept) and ``flush`` is
    handed ``{"samples": [(label, stack, n), ...], "dropped": d}``
    roughly twice a second; a False return (e.g. the worker pipe lock
    is busy) just retries next tick with the buffer intact.
    """

    def __init__(self, hz: float, flush: Callable[[dict], Any],
                 label_fn: Optional[Callable[[], Optional[str]]] = None,
                 all_threads: bool = False, max_keys: int = 2048,
                 flush_interval_s: float = 0.5,
                 name: str = "ray_tpu_profile_sampler") -> None:
        self.hz = float(hz)
        self._flush = flush
        self._label_fn = label_fn
        self._all_threads = all_threads
        self._max_keys = int(max_keys)
        self._flush_interval_s = float(flush_interval_s)
        self._main_id = threading.main_thread().ident
        # parked threads (the common head case in all_threads mode)
        # present the SAME live frame object at the same instruction
        # tick after tick — memoize their folded string instead of
        # re-walking up to _MAX_DEPTH frames per thread per sample
        self._fold_cache: Dict[Tuple[int, int, int], str] = {}
        self._buf: Dict[Tuple[str, str], int] = {}
        self._dropped = 0
        self.samples_taken = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)

    def start(self) -> "StackSampler":
        if self.hz > 0:
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    # -- sampler thread ------------------------------------------------
    def _run(self) -> None:
        period = 1.0 / max(self.hz, 1e-3)
        last_flush = time.monotonic()
        while not self._stop.wait(period):
            try:
                self._sample_once()
            except Exception:
                pass  # a torn frame walk must never kill the sampler
            now = time.monotonic()
            if self._buf and now - last_flush >= self._flush_interval_s:
                if self._try_flush():
                    last_flush = now
        self._try_flush()

    def _sample_once(self) -> None:
        own = threading.get_ident()
        names = ({t.ident: t.name for t in threading.enumerate()}
                 if self._all_threads else {})
        label = self._label_fn() if self._label_fn is not None else None
        for tid, frame in sys._current_frames().items():
            if tid == own:
                continue
            if not self._all_threads and tid != self._main_id:
                continue
            cache_key = (id(frame), id(frame.f_code), frame.f_lasti)
            stack = self._fold_cache.get(cache_key)
            if stack is None:
                if len(self._fold_cache) >= 4096:
                    self._fold_cache.clear()
                stack = self._fold_cache[cache_key] = fold_stack(frame)
            if not stack:
                continue
            lbl = (label if tid == self._main_id and label is not None
                   else (names.get(tid, "idle") if self._all_threads
                         else "idle"))
            key = (lbl, stack)
            if key not in self._buf and len(self._buf) >= self._max_keys:
                self._dropped += 1
                continue
            self._buf[key] = self._buf.get(key, 0) + 1
            self.samples_taken += 1

    def _try_flush(self) -> bool:
        if not self._buf and not self._dropped:
            return True
        buf, self._buf = self._buf, {}
        dropped, self._dropped = self._dropped, 0
        payload = {"samples": [(lbl, stack, n)
                               for (lbl, stack), n in buf.items()],
                   "dropped": dropped}
        try:
            if self._flush(payload) is False:
                raise RuntimeError("flush declined")
        except Exception:
            # put the counts back (merged) and retry on a later tick
            for (lbl, stack), n in buf.items():
                key = (lbl, stack)
                if key in self._buf or len(self._buf) < self._max_keys:
                    self._buf[key] = self._buf.get(key, 0) + n
                else:
                    self._dropped += 1
            self._dropped += dropped
            return False
        return True


# ----------------------------------------------------------------------
# per-node resource sampling
# ----------------------------------------------------------------------

class ResourceSampler:
    """Fixed-interval /proc + internal-gauge sampler on a daemon
    thread.  Each tick hands ``sink`` one payload dict::

        {"ts": <local wall clock>, "cpu_percent": ..., "rss_bytes": ...,
         "mem_used_bytes": ..., <gauge name>: <value>, ...}

    ``gauges`` maps extra series names to zero-arg callables (shm arena
    occupancy, scheduler queue depth, ...); a failing gauge reports 0
    rather than killing the loop.  The receiver aligns ``ts`` onto the
    head's clock axis with the link's clock_offset."""

    def __init__(self, interval_s: float, sink: Callable[[dict], Any],
                 gauges: Optional[Dict[str, Callable[[], float]]] = None,
                 name: str = "ray_tpu_resource_sampler") -> None:
        self.interval_s = float(interval_s)
        self._sink = sink
        self._gauges = dict(gauges or {})
        self._cpu = CpuPercent()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)

    def start(self) -> "ResourceSampler":
        if self.interval_s > 0:
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def sample(self) -> dict:
        used, _total = read_meminfo()
        payload: Dict[str, Any] = {
            "ts": time.time(),
            "cpu_percent": self._cpu.sample(),
            "rss_bytes": read_self_rss(),
            "mem_used_bytes": used,
        }
        for series, fn in self._gauges.items():
            try:
                payload[series] = fn()
            except Exception:
                payload[series] = 0
        return payload

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._sink(self.sample())
            except Exception:
                pass  # a dead link must never kill the sampler


# ----------------------------------------------------------------------
# head-side aggregation
# ----------------------------------------------------------------------

class UtilizationRing:
    """Bounded time series keyed by (node, series): ``maxlen`` points
    per key, fixed-interval downsampling — a sample arriving within
    ~80% of ``interval_s`` of the previous point REPLACES it (latest
    value wins, counted) so one flappy producer cannot advance the ring
    faster than the configured cadence.  Callers hold the owning
    plane's lock."""

    def __init__(self, interval_s: float, maxlen: int) -> None:
        self.interval_s = float(interval_s)
        self.maxlen = max(int(maxlen), 1)
        self._series: Dict[Tuple[int, str], deque] = {}
        self.points_recorded = 0
        self.points_downsampled = 0

    def record(self, node: int, series: str, ts: float,
               value: float) -> None:
        dq = self._series.get((node, series))
        if dq is None:
            dq = self._series[(node, series)] = deque(maxlen=self.maxlen)
        if dq and ts - dq[-1][0] < 0.8 * self.interval_s:
            dq[-1] = (dq[-1][0], value)
            self.points_downsampled += 1
            return
        dq.append((ts, value))
        self.points_recorded += 1

    def rows(self, node: Optional[int] = None,
             series: Optional[str] = None) -> List[dict]:
        out = []
        for (n, s), dq in sorted(self._series.items(),
                                 key=lambda kv: (kv[0][0], kv[0][1])):
            if node is not None and n != node:
                continue
            if series is not None and s != series:
                continue
            out.append({"node": n, "series": s,
                        "points": [[ts, v] for ts, v in dq]})
        return out

    def latest(self) -> Dict[int, Dict[str, float]]:
        """{node: {series: latest value}} for the metric gauges."""
        out: Dict[int, Dict[str, float]] = {}
        for (n, s), dq in self._series.items():
            if dq:
                out.setdefault(n, {})[s] = dq[-1][1]
        return out


class ProfilePlane:
    """Head-side flight-recorder state: the folded-stack count table +
    the utilization ring, fed by every node's samplers."""

    def __init__(self, hz: Optional[float] = None,
                 interval_s: Optional[float] = None,
                 util_maxlen: Optional[int] = None,
                 max_stacks: Optional[int] = None) -> None:
        from ray_tpu._private.config import GLOBAL_CONFIG
        self.hz = float(GLOBAL_CONFIG.profile_hz if hz is None else hz)
        if interval_s is None:
            interval_s = GLOBAL_CONFIG.utilization_interval_s
        if util_maxlen is None:
            util_maxlen = GLOBAL_CONFIG.utilization_ring
        if max_stacks is None:
            max_stacks = GLOBAL_CONFIG.profile_stacks_max
        self._max_stacks = int(max_stacks)
        self._lock = runtime_sanitizer.wrap_lock(
            threading.Lock(), "_private.profile_plane.ProfilePlane._lock")
        # (node, label, stack) -> count, least recently bumped first
        self._counts: "OrderedDict[Tuple[int, str, str], int]" \
            = OrderedDict()
        self.samples_recorded = 0
        self.samples_dropped = 0
        self.stacks_evicted = 0
        self.util = UtilizationRing(interval_s, util_maxlen)
        self._samplers: List[Any] = []

    # -- producers -----------------------------------------------------
    def record_batch(self, node: int, payload: dict) -> None:
        """One shipped profiler batch from ``node`` (see StackSampler
        flush payload shape)."""
        samples = payload.get("samples") or ()
        with self._lock:
            self.samples_dropped += int(payload.get("dropped", 0))
            counts = self._counts
            for label, stack, n in samples:
                key = (node, label or "idle", stack)
                cur = counts.get(key)
                if cur is None:
                    while len(counts) >= self._max_stacks:
                        counts.popitem(last=False)
                        self.stacks_evicted += 1
                    counts[key] = int(n)
                else:
                    counts[key] = cur + int(n)
                    counts.move_to_end(key)
                self.samples_recorded += int(n)

    def record_util(self, node: int, payload: dict,
                    offset: float = 0.0) -> None:
        """One resource sample from ``node``; ``offset`` maps the
        producer's wall clock onto the head's axis (0 for the head and
        local pools)."""
        ts = float(payload.get("ts", 0.0) or time.time()) + offset
        with self._lock:
            for series, value in payload.items():
                if series == "ts":
                    continue
                try:
                    self.util.record(node, series, ts, float(value))
                except (TypeError, ValueError):
                    continue

    # -- the head's own samplers ---------------------------------------
    def start_head_samplers(
            self,
            gauges: Optional[Dict[str, Callable[[], float]]] = None,
            label_fn: Optional[Callable[[], Optional[str]]] = None
            ) -> None:
        """Head node (index 0): a stack sampler over every thread in
        this process and a resource sampler carrying the cluster-internal
        gauges; both record straight into this plane, no wire hop."""
        stack = StackSampler(
            self.hz, lambda p: self.record_batch(0, p),
            label_fn=label_fn, all_threads=label_fn is None,
            name="ray_tpu_profile_head").start()
        res = ResourceSampler(
            self.util.interval_s, lambda p: self.record_util(0, p),
            gauges=gauges, name="ray_tpu_util_head").start()
        self._samplers.extend((stack, res))

    def shutdown(self) -> None:
        for s in self._samplers:
            try:
                s.stop()
            except Exception:
                pass
        self._samplers = []

    # -- consumers (state API / CLI / dashboard / metrics) -------------
    def profile_stacks(self) -> List[dict]:
        """One row per resident (node, task, stack), highest count
        first."""
        with self._lock:
            assert_holds(self._lock, "ProfilePlane stack table")
            items = list(self._counts.items())
        rows = [{"node": n, "task": lbl, "stack": stack, "count": c}
                for (n, lbl, stack), c in items]
        rows.sort(key=lambda r: -r["count"])
        return rows

    def list_utilization(self, node: Optional[int] = None,
                         series: Optional[str] = None) -> List[dict]:
        with self._lock:
            return self.util.rows(node=node, series=series)

    def utilization_latest(self) -> Dict[int, Dict[str, float]]:
        with self._lock:
            return self.util.latest()

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "samples_recorded": self.samples_recorded,
                "samples_dropped": self.samples_dropped,
                "stacks_evicted": self.stacks_evicted,
                "stacks_resident": len(self._counts),
                "util_points_recorded": self.util.points_recorded,
                "util_points_downsampled": self.util.points_downsampled,
            }


# ----------------------------------------------------------------------
# exports: collapsed stacks, speedscope, top-tasks table
# ----------------------------------------------------------------------

def collapsed(rows: List[dict]) -> str:
    """Brendan Gregg folded-stack text: ``node;task;frames count`` per
    line — feed straight into flamegraph.pl / inferno / speedscope."""
    out = []
    for r in rows:
        out.append(f"node{r['node']};{r['task']};{r['stack']} "
                   f"{r['count']}")
    return "\n".join(out) + ("\n" if out else "")


def top_tasks(rows: List[dict], limit: int = 15) -> List[dict]:
    """Samples aggregated by task label, highest CPU share first."""
    total = sum(r["count"] for r in rows) or 1
    by_task: Dict[Tuple[int, str], int] = {}
    for r in rows:
        key = (r["node"], r["task"])
        by_task[key] = by_task.get(key, 0) + r["count"]
    table = [{"node": n, "task": t, "samples": c,
              "cpu_pct": round(100.0 * c / total, 1)}
             for (n, t), c in by_task.items()]
    table.sort(key=lambda r: -r["samples"])
    return table[:limit]


def speedscope(rows: List[dict], name: str = "ray_tpu") -> dict:
    """speedscope.app sampled-profile JSON; every (node, task) prefix
    becomes the two outermost frames so the flamegraph groups by node
    then task."""
    frames: List[dict] = []
    index: Dict[str, int] = {}

    def fidx(fname: str) -> int:
        i = index.get(fname)
        if i is None:
            i = index[fname] = len(frames)
            frames.append({"name": fname})
        return i

    samples, weights = [], []
    for r in rows:
        chain = [f"node{r['node']}", r["task"]] + r["stack"].split(";")
        samples.append([fidx(f) for f in chain])
        weights.append(r["count"])
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled", "name": name, "unit": "none",
            "startValue": 0, "endValue": total,
            "samples": samples, "weights": weights,
        }],
        "name": name,
    }


def flamegraph_report(rows: List[dict]) -> dict:
    """The ``ray_tpu.profile()`` return shape: a speedscope document
    plus the collapsed text and a top-tasks-by-CPU table."""
    return {
        "samples": sum(r["count"] for r in rows),
        "top_tasks": top_tasks(rows),
        "collapsed": collapsed(rows),
        "speedscope": speedscope(rows),
    }
