"""Runtime environments: working_dir + pip, built on demand per node.

Reference surface: the per-node runtime env agent (ray:
python/ray/_private/runtime_env/ — working_dir packages upload once as
content-addressed zips to GCS storage and extract into a per-node
cache; pip environments build per spec and are shared by workers using
the same env).

Here:
  - ``working_dir``: the driver zips the directory (deterministic
    walk), content-addresses it (sha1), and stores the zip in the GCS
    KV under ``env_pkg:<hash>``. Workers fetch the bytes ONCE per node
    (owner RPC for process workers, direct KV for thread mode),
    extract into a per-node cache directory, and put the extracted
    root on sys.path (process workers also chdir for the task's
    duration — thread mode shares one process cwd and only gets the
    sys.path half, same caveat as thread-mode env_vars).
  - ``pip``: a venv per spec hash (``--system-site-packages`` so the
    baked scientific stack stays importable), built on first use per
    node with ``pip install --no-index --no-deps
    --no-build-isolation`` — this environment has NO network egress,
    so requirement strings must be local paths (a wheel or source
    directory); anything else fails with pip's own resolver error.
    The venv's site-packages prepends to sys.path around execution.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import subprocess
import sys
import tempfile
import threading
import zipfile
from typing import Dict, List, Optional, Tuple

_PKG_PREFIX = b"env_pkg:"
_pack_cache: Dict[str, Tuple[tuple, Tuple[str, bytes]]] = {}
_pack_lock = threading.Lock()

# build artifacts excluded from fingerprints AND packages: pip install
# of a source dir writes egg-info/build into it — fingerprinting those
# would rebuild the venv after every install, forever
_SKIP_DIRS = {"__pycache__", ".git", "build", "dist", ".eggs"}


def _skip(name: str) -> bool:
    return name in _SKIP_DIRS or name.endswith(".egg-info")


def _fingerprint(path: str) -> tuple:
    """(latest mtime, entry count) over a tree, excluding build
    artifacts; tolerant of files vanishing mid-walk."""
    try:
        latest = os.path.getmtime(path)
    except OSError:
        return (0.0, 0)
    count = 0
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if not _skip(d)]
        for name in list(dirs) + list(files):
            count += 1
            try:
                latest = max(latest,
                             os.path.getmtime(os.path.join(root, name)))
            except OSError:
                pass
    return (latest, count)


def package_working_dir(path: str) -> Tuple[str, bytes]:
    """(content hash, zip bytes) for a directory; cached by
    (abspath, latest mtime) so repeat submissions do not re-zip."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env working_dir {path!r} is not a "
                         "directory")
    # deleting sub/old.py bumps only sub's mtime, so the fingerprint
    # counts directory mtimes + entries too
    key = _fingerprint(path)
    with _pack_lock:
        cached = _pack_cache.get(path)
        # one entry PER PATH (validated by fingerprint): per-version
        # caching would retain every edit's zip for the process lifetime
        hit = cached[1] if cached is not None and cached[0] == key \
            else None
    if hit is not None:
        return hit
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if not _skip(d))
            for f in sorted(files):
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path)
                # fixed date: identical content -> identical hash
                info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
                with open(full, "rb") as fh:
                    z.writestr(info, fh.read())
    data = buf.getvalue()
    digest = hashlib.sha1(data).hexdigest()
    with _pack_lock:
        _pack_cache[path] = (key, (digest, data))
    return digest, data


def pip_spec_hash(pip: List[str]) -> str:
    """Spec hash INCLUDING the content fingerprint of local-path
    requirements: editing a local package must build a fresh venv, not
    silently reuse the stale install."""
    parts: List[str] = []
    for req in sorted(pip):
        entry = req
        if os.path.exists(req):
            if os.path.isdir(req):
                latest, count = _fingerprint(req)
            else:
                try:
                    latest, count = os.path.getmtime(req), 1
                except OSError:
                    latest, count = 0.0, 0
            entry = f"{req}@{latest}:{count}"
        parts.append(entry)
    return hashlib.sha1(json.dumps(parts).encode()).hexdigest()


class EnvManager:
    """Per-process environment cache (one per worker process / driver).
    The cache DIRECTORY is per-node shared (tempdir namespaced by uid)
    so sibling workers reuse extractions and venvs."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or os.path.join(
            tempfile.gettempdir(), f"ray_tpu_envs_{os.getuid()}")
        os.makedirs(os.path.join(self.cache_dir, "locks"), exist_ok=True)
        self._lock = threading.Lock()

    class _file_lock:
        """fcntl lock: the cache directory is shared by every worker
        PROCESS on the node, so builds/extractions need OS-level mutual
        exclusion, not just an in-process lock."""

        def __init__(self, cache_dir: str, name: str):
            self._path = os.path.join(cache_dir, "locks", name + ".lock")

        def __enter__(self):
            import fcntl

            self._f = open(self._path, "a")
            fcntl.flock(self._f.fileno(), fcntl.LOCK_EX)
            return self

        def __exit__(self, *exc):
            import fcntl

            fcntl.flock(self._f.fileno(), fcntl.LOCK_UN)
            self._f.close()
            return False

    # -- working_dir ----------------------------------------------------
    def ensure_working_dir(self, pkg_hash: str, fetch) -> str:
        """Extracted directory for a package hash; ``fetch()`` returns
        the zip bytes when not cached locally."""
        dest = os.path.join(self.cache_dir, f"wd_{pkg_hash}")
        marker = os.path.join(dest, ".ready")
        with self._lock, self._file_lock(self.cache_dir,
                                         f"wd_{pkg_hash}"):
            if os.path.exists(marker):
                return dest
            data = fetch()
            if data is None:
                raise RuntimeError(
                    f"runtime_env package {pkg_hash} not found in the "
                    "cluster KV store")
            import shutil

            tmp = f"{dest}.tmp.{os.getpid()}"
            shutil.rmtree(tmp, ignore_errors=True)
            with zipfile.ZipFile(io.BytesIO(data)) as z:
                z.extractall(tmp)
            # a partial dest (crashed extraction: no .ready) is replaced
            shutil.rmtree(dest, ignore_errors=True)
            os.replace(tmp, dest)
            open(marker, "w").close()
        return dest

    # -- pip ------------------------------------------------------------
    def ensure_pip(self, pip: List[str]) -> str:
        """site-packages path of the venv for this spec, building it on
        first use (local-path requirements only: no network egress)."""
        spec_hash = pip_spec_hash(pip)
        env_dir = os.path.join(self.cache_dir, f"pip_{spec_hash}")
        marker = os.path.join(env_dir, ".ready")
        with self._lock, self._file_lock(self.cache_dir,
                                         f"pip_{spec_hash}"):
            if not os.path.exists(marker):
                log_path = env_dir + ".log"
                with open(log_path, "ab") as log:
                    if not os.path.exists(
                            os.path.join(env_dir, "bin", "python")):
                        subprocess.run(
                            [sys.executable, "-m", "venv",
                             "--system-site-packages", env_dir],
                            check=True, stdout=log, stderr=log)
                    env_python = os.path.join(env_dir, "bin", "python")
                    r = subprocess.run(
                        [env_python, "-m", "pip", "install",
                         "--no-index", "--no-deps",
                         "--no-build-isolation", *pip],
                        stdout=log, stderr=log)
                if r.returncode != 0:
                    tail = open(log_path, "rb").read()[-2000:]
                    raise RuntimeError(
                        "runtime_env pip install failed (no network "
                        "egress: requirements must be local wheel/dir "
                        f"paths):\n{tail.decode(errors='replace')}")
                open(marker, "w").close()
        vi = sys.version_info
        return os.path.join(env_dir, "lib",
                            f"python{vi.major}.{vi.minor}",
                            "site-packages")


_manager: Optional[EnvManager] = None
_manager_lock = threading.Lock()


def get_manager() -> EnvManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = EnvManager()
        return _manager


class applied_env:
    """Context manager applying working_dir/pip around one execution:
    sys.path entries prepend (and pop after); process workers also
    chdir (``use_cwd=True`` — thread mode shares the process cwd and
    must not)."""

    def __init__(self, working_path: Optional[str],
                 site_packages: Optional[str], use_cwd: bool):
        self._wd = working_path
        self._sp = site_packages
        self._use_cwd = use_cwd
        self._prev_cwd: Optional[str] = None
        self._added: List[str] = []

    def __enter__(self):
        for p in (self._sp, self._wd):
            if p is not None:
                sys.path.insert(0, p)
                self._added.append(p)
        if self._wd is not None and self._use_cwd:
            self._prev_cwd = os.getcwd()
            os.chdir(self._wd)
        return self

    def __exit__(self, *exc):
        if self._prev_cwd is not None:
            try:
                os.chdir(self._prev_cwd)
            except OSError:
                pass
        for p in self._added:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        # purge modules imported FROM the env: workers are reused
        # across tasks with different (or no) runtime_envs, and
        # sys.modules caching would leak this env's imports into them
        # (the reference isolates by keying worker processes on the
        # env; module purge gives the same import-visibility contract)
        if self._added:
            prefixes = tuple(os.path.abspath(p) + os.sep
                             for p in self._added)
            for name, mod in list(sys.modules.items()):
                f = getattr(mod, "__file__", None)
                if f and os.path.abspath(f).startswith(prefixes):
                    del sys.modules[name]
        return False


def kv_key(pkg_hash: str) -> bytes:
    return _PKG_PREFIX + pkg_hash.encode()
