"""Cluster-state checkpoint/resume.

Reference role: GCS fault tolerance (ray: src/ray/gcs/ — with Redis
persistence the GCS restarts and replays its tables) plus SURVEY §5's
TPU-native addition: the checkpoint also captures the SCHEDULER'S
device-resident tensors, and pending work resubmits on restore (specs
travel by cloudpickle, results land under their ORIGINAL object ids so
pre-snapshot refs resolve in the restored session).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict

import cloudpickle

FORMAT_VERSION = 1


def save_cluster_state(worker, path: str) -> Dict[str, Any]:
    """Snapshot control-plane tables + scheduler state to ``path``."""
    gcs = worker.gcs

    def _started(task_id) -> bool:
        """Window-leased tasks queued behind a worker are resubmittable;
        anything observed executing (thread registry or leased onto a
        worker pipe) is not."""
        with worker._running_lock:
            if task_id in worker._running_tasks:
                return True
        for pool in list(worker._node_pools.values()):
            with pool._lock:
                if task_id in pool._by_task:
                    return True
        return False

    try:
        pending = worker.scheduler.pending_entries(_started)
    except TypeError:  # EventScheduler: no window leases exist
        pending = worker.scheduler.pending_entries()
    snap = {
        "version": FORMAT_VERSION,
        "time": time.time(),
        "kv": {f"{ns}\x00{k.decode('latin1')}": v
               for (ns, k), v in gcs._kv.items()},
        "jobs": {j.hex(): dict(meta) for j, meta in
                 gcs.job_table().items()},
        "actors": [
            {"actor_id": e.actor_id.hex(), "name": e.name,
             "namespace": e.namespace, "class_name": e.class_name,
             "state": e.state, "node_index": e.node_index}
            for e in gcs.actor_table()
        ],
        "placement_groups": worker.placement_groups.table(),
        "pending_tasks": [],
        "unsnapshottable_tasks": 0,
        "scheduler_arrays": worker.scheduler.device_state_snapshot(),
        "scheduler_stats": worker.scheduler.stats(),
    }
    for spec, deps in pending:
        try:
            blob = cloudpickle.dumps(spec)
        except Exception:
            # a spec closing over unpicklable state (locks, sockets)
            # cannot travel; count it honestly rather than failing the
            # whole snapshot
            snap["unsnapshottable_tasks"] += 1
            continue
        # deps recompute from the spec at restore; only the spec travels
        snap["pending_tasks"].append({"spec": blob})
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        cloudpickle.dump(snap, f)
    os.replace(tmp, path)
    return {"pending_tasks": len(snap["pending_tasks"]),
            "unsnapshottable_tasks": snap["unsnapshottable_tasks"],
            "kv_entries": len(snap["kv"]),
            "actors": len(snap["actors"])}


def load_cluster_state(worker, path: str) -> Dict[str, Any]:
    """Restore into a (fresh) session: KV entries re-populate, and every
    snapshotted pending task RESUBMITS — results store under the
    original return ids, so ObjectRefs reconstructed from the snapshot
    epoch resolve here. Actors are metadata-only in the snapshot (their
    instances died with the old process; the reference restarts them
    through the FSM — callers re-create from the recorded class names)."""
    with open(path, "rb") as f:
        snap = cloudpickle.load(f)
    if snap.get("version") != FORMAT_VERSION:
        raise ValueError(f"snapshot version {snap.get('version')} != "
                         f"{FORMAT_VERSION}")
    for key, v in snap["kv"].items():
        ns, _, k = key.partition("\x00")
        worker.gcs.kv_put(k.encode("latin1"), v, namespace=ns)

    from ray_tpu._private.scheduler.base import PendingTask

    resubmitted = 0
    for entry in snap["pending_tasks"]:
        spec = cloudpickle.loads(entry["spec"])
        return_ids = (getattr(spec, "_retry_return_ids", None)
                      or spec.return_ids())
        for oid in return_ids:
            worker.reference_counter.add_owned_object(
                oid, lineage_task=spec.task_id)
        from ray_tpu._private.worker import _top_level_deps

        deps = _top_level_deps(spec.args, spec.kwargs)
        worker.reference_counter.add_submitted_task_references(deps)
        worker.task_manager.add_pending(spec, deps)
        unresolved = [d for d in deps
                      if not worker.memory_store.contains(d)]
        for d in unresolved:
            worker.object_recovery.maybe_recover(d)
        worker.scheduler.submit(PendingTask(spec=spec, deps=unresolved,
                                            execute=lambda t, n: None))
        resubmitted += 1
    return {"resubmitted_tasks": resubmitted,
            "kv_entries": len(snap["kv"]),
            "snapshot_time": snap["time"],
            "actors_recorded": len(snap["actors"])}
