"""Memory monitor — kill-and-retry under host memory pressure.

Reference surface: the memory monitor (ray: src/ray/common/
memory_monitor.h + python/ray/_private/memory_monitor.py — when node
memory use crosses a threshold, the raylet kills the most recently
started retriable task with a retriable OutOfMemoryError instead of
letting the OS OOM-killer take the whole node).

Here: a driver thread samples /proc/meminfo; past the threshold it
evicts the MOST RECENTLY STARTED running task (last-in-first-killed —
the reference's policy, preserving the oldest/most-completed work):
process-mode victims are killed at the process level and fail with
OutOfMemoryError (retriable per TaskManager.should_retry). Thread-mode
tasks are NOT evicted (a thread cannot be forced to release memory, and
a cooperative cancel would mislabel the failure); pressure is logged —
process workers are the enforcement path.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional, Tuple

from ray_tpu import exceptions as rex
from ray_tpu._private.config import GLOBAL_CONFIG

logger = logging.getLogger(__name__)


def host_memory() -> Tuple[int, int]:
    """(used_bytes, total_bytes) from /proc/meminfo — the profile
    plane's shared parser (one /proc reader for the monitor, the
    utilization sampler, and anything else that needs host memory)."""
    from ray_tpu._private.profile_plane import read_meminfo

    return read_meminfo()


class MemoryMonitor:
    def __init__(self, worker, threshold: Optional[float] = None,
                 interval_s: Optional[float] = None):
        self._worker = worker
        self._threshold = (threshold if threshold is not None
                           else GLOBAL_CONFIG.memory_usage_threshold)
        self._interval = (interval_s if interval_s is not None
                          else GLOBAL_CONFIG.memory_monitor_interval_s)
        self._shutdown = threading.Event()
        self.num_kills = 0
        self._last_kill = float("-inf")
        self._thread: Optional[threading.Thread] = None
        if 0 < self._threshold < 1.0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="ray_tpu_memmon")
            self._thread.start()

    def _loop(self) -> None:
        while not self._shutdown.wait(self._interval):
            # the guard is the point: an exception here must never
            # silently disable OOM protection for the process lifetime
            try:
                used, total = host_memory()
                if total and used / total >= self._threshold:
                    self._evict(used, total)
            except Exception:
                logger.exception("memory monitor tick failed; retrying")

    def _evict(self, used: int, total: int) -> None:
        # cooldown: a SIGKILLed process needs time to be reaped and its
        # memory reclaimed; firing every poll would wipe every in-flight
        # task (and burn the victim's retries) during one spike
        now = time.monotonic()
        if now - self._last_kill < max(1.0, 4 * self._interval):
            return
        victim = self._pick_victim()
        if victim is None:
            return
        task_id, kill = victim
        logger.warning(
            "memory monitor: host at %.0f%% (>= %.0f%%); killing most "
            "recent task %s with retriable OutOfMemoryError",
            100 * used / total, 100 * self._threshold,
            task_id.hex()[:16])
        self.num_kills += 1
        self._last_kill = now
        kill()

    def _pick_victim(self):
        """Most recently started running task (process-mode first: a
        killed process actually frees memory)."""
        w = self._worker
        pools = list(w._node_pools.values())
        if w.process_pool is not None and w.process_pool not in pools:
            pools.append(w.process_pool)
        newest = None
        for pool in pools:
            if getattr(pool, "is_remote", False):
                continue  # remote workers don't consume HEAD host memory
            with pool._lock:
                handles = list(pool._handles)
                for h in handles:
                    if h.dead or not h.inflight:
                        continue
                    # newest LEASE on this worker (the last pipelined task)
                    exec_id, inf = next(reversed(h.inflight.items()))
                    if newest is None or inf.started_at > newest[0]:
                        newest = (inf.started_at, h, exec_id)
        if newest is not None:
            _, h, exec_id = newest

            def kill(h=h):
                h.oom_kill = True
                try:
                    h.proc.kill()
                except Exception:
                    pass

            return exec_id, kill
        # thread mode: a thread cannot be forced to release memory, and
        # the cooperative cancel flag would surface as a NON-retriable
        # TaskCancelledError (or do nothing once user code is running) —
        # log the pressure instead of mislabeling an eviction
        with w._running_lock:
            n_running = len(w._running_tasks)
        if n_running:
            logger.warning(
                "memory monitor: host over threshold with %d thread-mode "
                "tasks running; thread workers cannot be OOM-killed "
                "(use worker_mode=process for enforcement)", n_running)
        return None

    def shutdown(self) -> None:
        self._shutdown.set()
