"""In-process memory store + pluggable shared-memory backend.

Reference surfaces:
  - CoreWorkerMemoryStore (ray: src/ray/core_worker/store_provider/memory_store/)
    — small objects live in the owner process, get() without IPC.
  - Plasma store (ray: src/ray/object_manager/plasma/) — large objects in a
    per-node shared-memory arena with create→seal lifecycle and eviction.

Here the MemoryStore is the always-present in-process tier; a node-level
SharedMemoryStore (ray_tpu/_private/runtime/shm_store.py) holds large
objects for multi-process mode. Errors are stored as first-class values so
ray.get re-raises them (reference: RayError in the object store).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set

from ray_tpu._private.ids import ObjectID


class ObjectStoreFullError(Exception):
    pass


class _Entry:
    __slots__ = ("value", "is_exception", "size", "insert_time")

    def __init__(self, value: Any, is_exception: bool, size: int):
        self.value = value
        self.is_exception = is_exception
        self.size = size
        self.insert_time = time.monotonic()


class MemoryStore:
    """Thread-safe in-process object store with readiness callbacks."""

    def __init__(self):
        self._objects: Dict[ObjectID, _Entry] = {}
        self._lock = threading.Lock()
        self._callbacks: Dict[ObjectID, List[Callable[[], None]]] = {}

    # -- write -------------------------------------------------------------
    def put(self, object_id: ObjectID, value: Any, *, is_exception: bool = False,
            size: int = 0) -> None:
        with self._lock:
            self._objects[object_id] = _Entry(value, is_exception, size)
            # waiters are callback-based (_await_count), nobody blocks
            # on this lock itself — no notify needed
            callbacks = self._callbacks.pop(object_id, None)
        if callbacks:
            for cb in callbacks:
                cb()

    # -- read --------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def missing_of(self, object_ids: List[ObjectID]) -> List[ObjectID]:
        """Ids NOT present, under one lock hold (a batch get() of 50k
        refs would otherwise pay 50k lock acquisitions up front)."""
        with self._lock:
            objects = self._objects
            return [o for o in object_ids if o not in objects]

    def get_entry(self, object_id: ObjectID) -> Optional[_Entry]:
        with self._lock:
            return self._objects.get(object_id)

    def _await_count(self, object_ids: List[ObjectID], need: int,
                     timeout: Optional[float]) -> int:
        """Block until ``need`` of object_ids are present (or timeout).

        Counter-based: each missing id gets ONE decrement callback, so a
        batch get() of N refs costs O(N) total instead of O(N) rescans
        per arrival (O(N^2), which capped e2e throughput at ~600
        tasks/s). Returns the number still missing (0 = satisfied)."""
        done = threading.Event()
        state_lock = threading.Lock()
        with self._lock:
            pending = {o for o in object_ids if o not in self._objects}
            need_more = need - (len(set(object_ids)) - len(pending))
            if need_more <= 0:
                return 0
            counter = [need_more]  # arrivals still required

            def on_ready() -> None:
                with state_lock:
                    counter[0] -= 1
                    fire = counter[0] == 0
                if fire:
                    done.set()

            for o in pending:
                self._callbacks.setdefault(o, []).append(on_ready)
        satisfied = done.wait(timeout=timeout)
        # Deregister leftover callbacks: a timed-out waiter (or one
        # satisfied by a subset, num_returns < len) would otherwise leak
        # one closure per still-pending id on EVERY call — unbounded
        # growth under the canonical poll loop `while: wait(refs, 1, t)`.
        with self._lock:
            for o in pending:
                lst = self._callbacks.get(o)
                if lst is not None:
                    try:
                        lst.remove(on_ready)
                    except ValueError:
                        pass
                    if not lst:
                        del self._callbacks[o]
            if satisfied:
                return 0
            return sum(1 for o in set(object_ids) if o not in self._objects)

    def wait_and_get(self, object_ids: List[ObjectID],
                     timeout: Optional[float]) -> List[_Entry]:
        """Block until all ids present (or timeout); returns entries in order."""
        n_missing = self._await_count(object_ids, len(set(object_ids)), timeout)
        with self._lock:
            if n_missing:
                missing = [o for o in object_ids if o not in self._objects]
                raise TimeoutError(
                    f"{len(missing)} objects not ready within timeout: "
                    f"{[m.hex()[:16] for m in missing[:3]]}"
                )
            entries = []
            for o in object_ids:
                entry = self._objects.get(o)
                if entry is None:
                    # deleted between the readiness wait and this read
                    # (ref-count release racing a get)
                    from ray_tpu.exceptions import ObjectLostError

                    raise ObjectLostError(
                        f"object {o.hex()[:16]} was freed while being read")
                entries.append(entry)
            return entries

    def wait(self, object_ids: List[ObjectID], num_returns: int,
             timeout: Optional[float]) -> Set[ObjectID]:
        """Return the set of ready ids once num_returns are ready or timeout."""
        self._await_count(object_ids, num_returns, timeout)
        with self._lock:
            return {o for o in object_ids if o in self._objects}

    def add_ready_callback(self, object_id: ObjectID, cb: Callable[[], None]):
        fire = False
        with self._lock:
            if object_id in self._objects:
                fire = True
            else:
                self._callbacks.setdefault(object_id, []).append(cb)
        if fire:
            cb()

    # -- lifecycle ---------------------------------------------------------
    def delete(self, object_ids: List[ObjectID]) -> None:
        # Callbacks are NOT dropped: a waiter blocked on a not-yet-stored
        # object must still wake when the value (or its reconstruction)
        # arrives — delete-before-put would otherwise strand it forever.
        with self._lock:
            for o in object_ids:
                self._objects.pop(o, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)

    def entries(self) -> List[tuple]:
        """Snapshot of (object_id, entry) pairs (state observability)."""
        with self._lock:
            return list(self._objects.items())

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.size for e in self._objects.values())
