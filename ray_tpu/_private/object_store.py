"""In-process memory store + pluggable shared-memory backend.

Reference surfaces:
  - CoreWorkerMemoryStore (ray: src/ray/core_worker/store_provider/memory_store/)
    — small objects live in the owner process, get() without IPC.
  - Plasma store (ray: src/ray/object_manager/plasma/) — large objects in a
    per-node shared-memory arena with create→seal lifecycle and eviction.

Here the MemoryStore is the always-present in-process tier; a node-level
SharedMemoryStore (ray_tpu/_private/runtime/shm_store.py) holds large
objects for multi-process mode. Errors are stored as first-class values so
ray.get re-raises them (reference: RayError in the object store).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set

from ray_tpu._private.ids import ObjectID


class ObjectStoreFullError(Exception):
    pass


class _Entry:
    __slots__ = ("value", "is_exception", "size", "insert_time")

    def __init__(self, value: Any, is_exception: bool, size: int):
        self.value = value
        self.is_exception = is_exception
        self.size = size
        self.insert_time = time.monotonic()


class MemoryStore:
    """Thread-safe in-process object store with readiness callbacks."""

    def __init__(self):
        self._objects: Dict[ObjectID, _Entry] = {}
        self._lock = threading.Condition()
        self._callbacks: Dict[ObjectID, List[Callable[[], None]]] = {}

    # -- write -------------------------------------------------------------
    def put(self, object_id: ObjectID, value: Any, *, is_exception: bool = False,
            size: int = 0) -> None:
        with self._lock:
            self._objects[object_id] = _Entry(value, is_exception, size)
            callbacks = self._callbacks.pop(object_id, [])
            self._lock.notify_all()
        for cb in callbacks:
            cb()

    # -- read --------------------------------------------------------------
    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get_entry(self, object_id: ObjectID) -> Optional[_Entry]:
        with self._lock:
            return self._objects.get(object_id)

    def wait_and_get(self, object_ids: List[ObjectID],
                     timeout: Optional[float]) -> List[_Entry]:
        """Block until all ids present (or timeout); returns entries in order."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                missing = [o for o in object_ids if o not in self._objects]
                if not missing:
                    return [self._objects[o] for o in object_ids]
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"{len(missing)} objects not ready within timeout: "
                        f"{[m.hex()[:16] for m in missing[:3]]}"
                    )
                self._lock.wait(timeout=remaining)

    def wait(self, object_ids: List[ObjectID], num_returns: int,
             timeout: Optional[float]) -> Set[ObjectID]:
        """Return the set of ready ids once num_returns are ready or timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                ready = {o for o in object_ids if o in self._objects}
                if len(ready) >= num_returns:
                    return ready
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return ready
                self._lock.wait(timeout=remaining)

    def add_ready_callback(self, object_id: ObjectID, cb: Callable[[], None]):
        fire = False
        with self._lock:
            if object_id in self._objects:
                fire = True
            else:
                self._callbacks.setdefault(object_id, []).append(cb)
        if fire:
            cb()

    # -- lifecycle ---------------------------------------------------------
    def delete(self, object_ids: List[ObjectID]) -> None:
        with self._lock:
            for o in object_ids:
                self._objects.pop(o, None)
                self._callbacks.pop(o, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.size for e in self._objects.values())
