"""Head-side runtime for remote (off-head) nodes.

Reference surfaces: the head's view of a remote raylet — ray
src/ray/raylet_client/ (lease/cancel RPCs to a node), the object
manager's cross-node half (src/ray/object_manager/: Pull/Push of object
chunks between nodes), and the GCS object directory
(src/ray/object_manager/ownership_object_directory.cc) that maps objects
to the nodes holding their primary copy.

``RemoteNodePool`` subclasses ProcessWorkerPool so every owner-side
protocol (lease grants, retries, borrower bookkeeping, the whole actor
message protocol) is byte-identical for local and remote nodes; only
the transport differs. Worker pipes become proxy sends over the single
head<->daemon connection; a demux thread fans incoming daemon traffic
out to per-worker queues (preserving per-worker message order, exactly
like the local per-worker reader threads). Object movement:

  - task results stay in the PRODUCING node's arena; the head stores a
    ``RemotePlaceholder`` and records the location in the GCS object
    directory (bytes cross the wire only on first cross-node use);
  - a dep already resident on the target node ships as a ``_PullValue``
    marker the worker resolves from its local arena zero-copy;
  - a dep living on the head (or a third node) is embedded in the task
    payload — fetched head-side first if needed (head-mediated
    transfer; the reference does node-to-node pushes, which this
    protocol admits later by handing the daemon a peer address instead
    of inline bytes);
  - daemon connection loss IS node-failure detection (the DCN story:
    a dead TCP link marks the node dead, like the reference's
    health-check RPC timeouts).
"""

from __future__ import annotations

import logging
import os
import queue
import subprocess
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from multiprocessing.connection import Listener
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu import exceptions as rex
from ray_tpu._private.analysis import runtime_sanitizer
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime.process_pool import (_DepError, _Handle,
                                                   _InFlight, _RequeueDeps,
                                                   ProcessWorkerPool,
                                                   RemotePlaceholder)
from ray_tpu._private.runtime.worker_process import _PullValue
from ray_tpu._private.serialization import serialize

logger = logging.getLogger(__name__)


class _ProxyConn:
    """Send-only facade standing in for a worker pipe: routes through
    the daemon link tagged with the worker number."""

    __slots__ = ("_pool", "_num", "_channel")

    def __init__(self, pool: "RemoteNodePool", num: int, channel: str):
        self._pool = pool
        self._num = num
        self._channel = channel

    def send(self, msg) -> None:
        self._pool._send_daemon((self._channel, self._num, msg))

    def close(self) -> None:
        pass


class RemoteNodePool(ProcessWorkerPool):
    is_remote = True

    def __init__(self, worker, num_workers: int, node_index: int, conn,
                 node_id, daemon_proc: Optional[subprocess.Popen] = None,
                 arena_name: Optional[str] = None,
                 peer_address: Optional[tuple] = None,
                 fenced: bool = False):
        self._arena_name = arena_name
        # epoch fence (node-death FT): a daemon that rejoins AFTER the
        # head declared its node dead gets a fenced pool — outbox
        # REPLAY envelopes (receipts stranded from the dead era) are
        # acked but never dispatched, because the head already failed
        # or resubmitted everything that era produced; processing a
        # stale lease/completion replay would double-resolve it. Fresh
        # (non-replay) traffic flows normally.
        self._fenced = fenced
        # daemon's direct-transfer endpoint (object manager peer plane):
        # other nodes pull object bytes straight from it, head-free
        self.peer_address = tuple(peer_address) if peer_address else None
        self._conn = conn
        self._conn_lock = threading.Lock()
        self._conn_dead = False
        # head->daemon messages that failed (or arrived while the link
        # was down) wait here and flush in order on re-attach; an
        # escalated node death discards them (their tasks retry through
        # the normal inflight bookkeeping)
        self._pending_sends: List[tuple] = []
        # outbox bookkeeping (daemon->head exactly-once): highest
        # sequence number processed, re-attach generation (stale
        # link-loss callbacks and grace timers check it), and the
        # failover observability counters metrics.py exports
        self._seq_lock = threading.Lock()
        self._last_seen_seq = 0
        self._attach_gen = 0
        self.outbox_depth = 0
        self.outbox_replayed = 0
        self.node_id = node_id
        self._daemon_proc = daemon_proc
        # two-level dispatch observability: task-id binaries of leases
        # the node's LocalScheduler admitted that are still in flight
        # (their completions resolve through the adopted-lease path),
        # and a lifetime counter — both surfaced by state.list_nodes
        self._local_tids: set = set()
        self.local_dispatched = 0
        # monotonic timestamp of the last resview push to this node's
        # daemon (state.list_nodes surfaces it as resview_age_s)
        self._resview_t: Optional[float] = None
        self._hqueues: Dict[int, queue.Queue] = {}
        self._fetches: Dict[int, Tuple[threading.Event, list]] = {}
        self._pings: Dict[int, Tuple[threading.Event, list]] = {}
        self._logreqs: Dict[int, Tuple[threading.Event, list]] = {}
        self._req_seq = 0
        self._req_lock = threading.Lock()
        # blocking worker RPCs (get/wait) must not stall the demux
        # thread; per-worker ordering is preserved by the handle queues,
        # and rpc replies are request-id-matched worker-side
        self._rpc_pool = ThreadPoolExecutor(
            max_workers=max(num_workers + 2, 4),
            thread_name_prefix="ray_tpu_remote_rpc")
        super().__init__(worker, num_workers, None, node_index=node_index)

    # -- transport -----------------------------------------------------
    def _start_transport(self) -> None:
        threading.Thread(target=self._demux_loop, daemon=True,
                         name=f"ray_tpu_remote_demux_{self.node_index}"
                         ).start()

    def _send_daemon(self, msg: tuple) -> None:
        with self._conn_lock:
            if not self._conn_dead:
                try:
                    self._conn.send(msg)
                    return
                except (OSError, ValueError):
                    pass  # demux EOF handles the failure; buffer below
            # a send() that raises never delivered a complete frame
            # (the daemon drops truncated frames with the connection),
            # so re-sending after re-attach cannot double-deliver
            self._pending_sends.append(msg)

    def _next_req(self) -> int:
        with self._req_lock:
            self._req_seq += 1
            return self._req_seq

    def _demux_loop(self) -> None:
        conn = self._conn
        with self._seq_lock:
            gen = self._attach_gen
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError, TypeError, ValueError):
                # TypeError/ValueError: conn closed under a blocked recv
                self._on_daemon_lost(gen)
                return
            runtime_sanitizer.check_wire("daemon_to_head", msg)
            if msg[0] == "seq":
                # outbox envelope: dedup by per-node sequence number
                # (a replay after a transient flap re-delivers entries
                # this head already processed), then ack the high-water
                # mark so the daemon trims its buffer
                _, seq, depth, is_replay, inner = msg
                with self._seq_lock:
                    duplicate = seq <= self._last_seen_seq
                    if not duplicate:
                        self._last_seen_seq = seq
                    high_water = self._last_seen_seq
                    self.outbox_depth = depth
                    if is_replay:
                        self.outbox_replayed += 1
                self._send_daemon(("ack", high_water))
                if duplicate:
                    continue
                if is_replay and getattr(self, "_fenced", False):
                    # stale-era replay into a fenced (rejoined-after-
                    # declared-dead) pool: ack'd above so the daemon
                    # trims its outbox, but never dispatched — the
                    # node-death reconciler already settled this era
                    self._worker.note_two_level("orphan_fenced")
                    continue
                runtime_sanitizer.check_wire("daemon_to_head", inner)
                msg = inner
            self._dispatch_daemon_msg(msg)

    def _dispatch_daemon_msg(self, msg: tuple) -> None:
        kind = msg[0]
        if kind == "w":
            num, wmsg = msg[1], msg[2]
            with self._lock:
                h = self._by_num.get(num)
            q = self._hqueues.get(num)
            if h is not None and q is not None:
                q.put(wmsg)
        elif kind == "worker_died":
            q = self._hqueues.get(msg[1])
            if q is not None:
                # msg may carry the worker's .err tail (the remote
                # crash traceback) — fold it into the cause so
                # WorkerCrashedError surfaces the real reason
                cause = f"exit code {msg[2]}"
                if len(msg) > 3 and msg[3]:
                    cause += msg[3]
                q.put(("__died__", cause))
        elif kind == "fetched":
            slot = self._fetches.pop(msg[1], None)
            if slot is not None:
                slot[1][:] = [msg[2], msg[3]]
                slot[0].set()
        elif kind == "pong":
            slot = self._pings.pop(msg[1], None)
            if slot is not None:
                slot[1][:] = [msg[2]]
                slot[0].set()
        elif kind == "log":
            # appended capture lines shipped by the daemon's tailer
            lm = getattr(self._worker, "log_monitor", None)
            if lm is not None:
                lm.on_remote_lines(self, msg[1], msg[2])
        elif kind in ("log_listed", "log_data"):
            slot = self._logreqs.pop(msg[1], None)
            if slot is not None:
                slot[1][:] = list(msg[2:])
                slot[0].set()
        elif kind == "pulled":
            # a staged (or localization) peer pull completed: this
            # node now holds a COPY — register it as a secondary
            # location so later leases can score/stage against it,
            # and count the cross-node bytes moved
            oid = ObjectID(msg[1])
            self._worker.gcs.object_location_add_secondary(
                oid, self.node_index)
            e = self._worker.memory_store.get_entry(oid)
            if e is not None and e.size:
                self._worker.note_transfer("bytes_pulled", e.size)
        elif kind == "clock":
            # clock handshake sample sent right after the daemon's
            # hello (and after every rejoin): maps daemon wall-clock
            # timestamps onto the head's axis. Error ~ one-way link
            # latency, far below task-span granularity.
            self.clock_offset = time.time() - msg[1]
        elif kind == "util":
            # outbox-riding resource sample from the daemon's sampler;
            # the payload's "ts" is daemon wall clock — align it onto
            # the head axis with the same offset the event planes use
            pp = getattr(self._worker, "profile_plane", None)
            if pp is not None:
                pp.record_util(self.node_index, msg[1],
                               offset=self.clock_offset)
        elif kind == "local_lease":
            # the node's LocalScheduler admitted a worker-submitted
            # task without a head round-trip: journal + adopt it so
            # failover reconciliation and ref bookkeeping see it as if
            # the head had placed it (outbox FIFO guarantees this
            # arrives before the lease's own done/err)
            with self._seq_lock:
                self._local_tids.add(msg[1])
                self.local_dispatched += 1
            self._worker.on_local_lease(self, msg[1], msg[2])
        elif kind == "local_retry":
            # the daemon re-leased a locally-dispatched task's failed
            # attempt to a sibling worker (per-attempt accounting, no
            # head round-trip): move the adopted inflight entry to the
            # new worker and re-journal the bumped attempt token. FIFO
            # puts this BEFORE the worker_died report, which then no
            # longer finds the lease on the dead handle
            self._worker.on_local_retry(self, msg[1], msg[2])
        elif kind == "p2p_done":
            # sequenced completion receipt for a peer-to-peer actor
            # call: results already flowed peer→peer; the head only
            # stores lineage/ownership (exactly-once vs any fallback)
            self._worker.on_p2p_done(self, msg[1], msg[2])
        elif kind == "p2p_fallback":
            # a peer lane died/dropped/timed out mid-call: re-execute
            # through the head path with the same attempt token; the
            # worker-side dedup cache makes the retry exactly-once
            self._worker.on_p2p_fallback(self, msg[1], msg[2])
        elif kind == "aresolve":
            # daemon asks where an actor lives (first p2p call to it)
            route = self._worker.resolve_actor_address(msg[1])
            self._send_daemon(("aroute", msg[1], route))
        elif kind == "fault":
            # a chaos injection fired on the daemon (peer_link site):
            # merge into the head controller's log and counters
            from ray_tpu._private.chaos import get_controller
            get_controller().note_remote(msg[1])
        else:
            # exhaustive dispatch: an unknown daemon tag means the
            # wire protocol drifted (raylint pass 3 checks this
            # statically; this guard catches version skew at runtime)
            logger.error(
                "head: unknown daemon message tag %r from node %d "
                "(protocol drift?)", kind, self.node_index)

    def _on_daemon_lost(self, gen: Optional[int] = None) -> None:
        from ray_tpu._private.config import GLOBAL_CONFIG

        with self._seq_lock:
            if gen is not None and gen != self._attach_gen:
                return  # a re-attach superseded this link already
            self._conn_dead = True
        # unblock fetch/ping/log waiters: their replies died with the
        # link regardless of whether the node comes back
        for table in (self._fetches, self._pings, self._logreqs):
            for ev, _slot in list(table.values()):
                ev.set()
            table.clear()
        grace = GLOBAL_CONFIG.daemon_rejoin_grace_s
        proc = self._daemon_proc
        if proc is not None and proc.poll() is None \
                and getattr(self, "_respawn_disabled", False):
            # machine-death chaos killpg'd the tree: the socket EOF can
            # beat the zombie transition by a scheduler tick, and
            # poll() alone would misread a corpse as a live daemon
            # worth a full rejoin grace window
            try:
                proc.wait(timeout=0.5)
            except Exception:
                pass
        daemon_known_dead = proc is not None and proc.poll() is not None
        if (grace > 0 and not daemon_known_dead and not self._shutdown
                and not self._node_dead
                and self._worker.gcs.mark_node_rejoining(self.node_id)):
            # REJOINING grace window: keep worker handles and in-flight
            # leases alive — a daemon that re-dials within the window
            # re-attaches (outbox replay + send-buffer flush) and the
            # blackout is invisible. A head-spawned daemon whose process
            # already exited can never re-dial: skip straight to death.
            logger.warning(
                "node %s: daemon link lost; REJOINING grace %.1fs",
                self.node_id.hex()[:16], grace)
            threading.Thread(
                target=self._grace_timer, args=(gen, grace), daemon=True,
                name=f"ray_tpu_rejoin_grace_{self.node_index}").start()
            return
        self._fail_lost_daemon()

    def _grace_timer(self, gen: Optional[int], grace: float) -> None:
        time.sleep(grace)
        with self._seq_lock:
            if gen is not None and gen != self._attach_gen:
                return  # the daemon re-attached in time
            if not self._conn_dead:
                return
        logger.warning("node %s: rejoin grace expired; marking dead",
                       self.node_id.hex()[:16])
        self._fail_lost_daemon()

    def _fail_lost_daemon(self) -> None:
        with self._conn_lock:
            self._pending_sends.clear()
        # declare the node dead BEFORE waking the per-worker queue
        # loops: their __died__ handling restarts actors, and a
        # restart that races the _node_dead flag re-spawns onto this
        # very corpse (burning a restart attempt on a worker that can
        # never register)
        if not self._shutdown and not self._node_dead:
            logger.warning("node %s: daemon connection lost; marking dead",
                           self.node_id.hex()[:16])
            try:
                self._worker.on_node_failure(
                    self.node_id, reason="daemon connection lost")
            except Exception:
                logger.exception("on_node_failure failed")
        # snapshot: _queue_loop threads pop _hqueues as they die
        for q in list(self._hqueues.values()):
            q.put(("__died__", "daemon connection lost"))
        self._unlink_dead_arena()

    def reattach(self, conn) -> None:
        """The daemon re-dialed after a transient link loss (the head
        never died): swap in the fresh connection, flush the buffered
        head->daemon sends in order, and restart the demux. The
        daemon's outbox replay arrives next and the sequence dedup in
        _demux_loop drops everything this head already processed."""
        with self._seq_lock:
            self._attach_gen += 1  # invalidates stale loss callbacks
        with self._conn_lock:
            old = self._conn
            self._conn = conn
            self._conn_dead = False
            pending, self._pending_sends = self._pending_sends, []
        try:
            old.close()
        except Exception:
            pass
        for msg in pending:
            self._send_daemon(msg)
        self._start_transport()
        self._worker.gcs.mark_node_rejoined(self.node_id)
        logger.warning("node %s: daemon re-attached (%d buffered sends "
                       "flushed)", self.node_id.hex()[:16], len(pending))

    def sever_link(self) -> None:
        """Chaos (``head`` site, kind ``flap``): close the daemon link
        without telling anyone. Both sides see EOF — the daemon enters
        its rejoin loop, this pool enters the REJOINING grace window,
        and the reunion exercises outbox replay + dedup end to end."""
        with self._conn_lock:
            try:
                self._conn.close()
            except Exception:
                pass

    def _unlink_dead_arena(self) -> None:
        """A SIGKILLed daemon can't unlink its own arena; reap it once
        the daemon is confirmed gone. Head-spawned daemons: wait on the
        child process. Adopted (CLI-joined) daemons: the severed
        connection is the death signal; a joined daemon on ANOTHER host
        leaves no segment here, so the by-name reap is a no-op there."""
        if self._arena_name is None:
            return
        if self._daemon_proc is not None:
            try:
                self._daemon_proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                return
        elif not self._conn_dead:
            return
        from multiprocessing import shared_memory
        try:
            seg = shared_memory.SharedMemory(name=self._arena_name)
            seg.close()
            seg.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            logger.debug("arena reap failed", exc_info=True)
        self._arena_name = None

    # -- worker lifecycle ----------------------------------------------
    def _spawn(self) -> _Handle:
        with self._lock:
            self._worker_seq += 1
            num = self._worker_seq
        h = _Handle(num)
        h.conn = _ProxyConn(self, num, "to_w")
        h.ctrl = _ProxyConn(self, num, "to_ctrl")
        q: queue.Queue = queue.Queue()
        self._hqueues[num] = q
        with self._lock:
            self._by_num[num] = h
        threading.Thread(target=self._queue_loop, args=(h, q), daemon=True,
                         name=f"ray_tpu_remote_w{num}").start()
        # the wid names the worker's capture files daemon-side, so log
        # filenames look identical on local and remote nodes
        self._send_daemon(("spawn", num, h.worker_id.hex()[:12]))
        return h

    def adopt_worker(self, num: int, pid: Optional[int],
                     is_actor: bool, busy: bool = False) -> _Handle:
        """Attach a handle to a worker process that ALREADY RUNS on the
        rejoining daemon (head-restart re-adoption): same plumbing as
        _spawn minus the spawn message — the process is alive, so it is
        ready by construction. ``busy`` marks workers the daemon
        reported with leases still executing: they are adopted into the
        handle set but NOT parked idle (adopt_inflight re-attaches
        their leases next; completion releases them normally)."""
        with self._lock:
            self._worker_seq = max(self._worker_seq, num)
        h = _Handle(num)
        h.conn = _ProxyConn(self, num, "to_w")
        h.ctrl = _ProxyConn(self, num, "to_ctrl")
        h.pid = pid
        h.ready = True
        q: queue.Queue = queue.Queue()
        self._hqueues[num] = q
        with self._lock:
            self._by_num[num] = h
        threading.Thread(target=self._queue_loop, args=(h, q), daemon=True,
                         name=f"ray_tpu_remote_w{num}").start()
        if not is_actor:
            with self._lock:
                self._handles.append(h)
            if not busy:
                self._mark_idle(h)
        return h

    def adopt_inflight(self, h: _Handle, task_id_bin: bytes,
                       return_bins: List[bytes], attempt: int) -> None:
        """Re-attach a lease a rejoining daemon reported still running:
        a SYNTHETIC inflight entry (pending=None, see _InFlight) keyed
        under the ORIGINAL return oids, so the daemon's eventual
        done/err (possibly arriving via outbox replay) resolves the
        exact refs a resumed ray:// client is blocked on."""
        task_id = TaskID(task_id_bin)
        inf = _InFlight(None, [ObjectID(b) for b in return_bins])
        with self._lock:
            h.inflight[task_id] = inf
            self._by_task[task_id] = h

    def send_resview(self, view: dict) -> None:
        """Push the head's resource/knob view to the node daemon: the
        LocalScheduler admits against this (accept gate, queue cap,
        p2p flag, job binary, residency digest, peer list, mirrored
        chaos plan). Sent only while a two-level knob is on — both off
        means zero wire delta. The push timestamp feeds
        state.list_nodes' resview_age_s freshness column."""
        self._send_daemon(("resview", view))
        self._resview_t = time.monotonic()

    def local_queue_depth(self) -> int:
        with self._seq_lock:
            return len(self._local_tids)

    # -- failover lease journal ----------------------------------------
    def _journal_lease(self, spec, payload: dict) -> None:
        """Mirror this dispatch into the GCS WAL so a restarted head
        can resubmit it if no surviving daemon claims it. Args are
        re-pickled from the RAW spec (the payload's args_blob embeds
        arena markers that die with this head); tasks whose args can't
        be pickled journal a record without a resubmit body — their
        adoption bookkeeping still works, resubmission fails the refs."""
        import cloudpickle as _cp

        try:
            args_blob = _cp.dumps((spec.args, spec.kwargs))
        except Exception:
            args_blob = None
        self._worker.gcs.journal_lease(spec.task_id.binary(), {
            "name": spec.name,
            "fn_blob": payload.get("fn_blob"),
            "args_blob": args_blob,
            "num_returns": spec.num_returns,
            "returns": list(payload["return_ids"]),
            "resources": dict(spec.resources or {}),
            "attempt": spec.attempt_number,
            "max_retries": spec.max_retries,
            "node_index": self.node_index,
        })

    def _assign(self, h: _Handle, pending, payload: dict) -> None:
        if self._worker.gcs.journal_enabled:
            self._journal_lease(pending.spec, payload)
        super()._assign(h, pending, payload)

    def _assign_many(self, h: _Handle, items: List[tuple]) -> None:
        if self._worker.gcs.journal_enabled:
            for pending, payload in items:
                self._journal_lease(pending.spec, payload)
        if self._envelope_on():
            # tentpole (c): the PR-11 batched lease envelope extends to
            # remote pools — one ("env", blob) frame rides the daemon
            # link with the same invariant-header/fn-blob trims as the
            # local shm ring. _assign_many_ring's pipe fallback is the
            # sender (remote handles have no ring), the daemon decodes
            # a bookkeeping copy and forwards the blob verbatim to the
            # worker's existing "env" pipe branch
            self._assign_many_ring(h, items)
            return
        super()._assign_many(h, items)

    def _envelope_on(self) -> bool:
        # rides the local_dispatch escape hatch: knobs off keeps the
        # head->daemon wire byte-for-byte pre-two-level ("tasks" lists)
        from ray_tpu._private.config import GLOBAL_CONFIG

        return bool(GLOBAL_CONFIG.local_dispatch
                    and GLOBAL_CONFIG.control_ring)

    def _finish_task(self, pending, exec_task_id: TaskID, retry) -> None:
        # terminal for THIS remote attempt (a retry re-journals at its
        # own dispatch): drop it from the reconciliation set so a later
        # failover can never resubmit an attempt that already resolved
        self._lease_done(exec_task_id)
        super()._finish_task(pending, exec_task_id, retry)

    def _lease_done(self, task_id: TaskID) -> None:
        with self._seq_lock:
            self._local_tids.discard(task_id.binary())
        self._worker.gcs.journal_lease_done(task_id.binary())

    def _queue_loop(self, h: _Handle, q: queue.Queue) -> None:
        """Per-worker message pump — the remote analog of the local
        per-worker reader thread (same ordering guarantees)."""
        while True:
            msg = q.get()
            if msg[0] == "__died__":
                self._hqueues.pop(h.worker_num, None)
                self._on_worker_failure(h, msg[1])
                return
            if msg[0] == "rpc":
                # blocking get/wait must not stall this worker's pump
                # either: an actor's kill/exit travels h.conn, but
                # completions for OTHER workers (which a get may await)
                # come through other queues — only same-worker ordering
                # matters, and a worker blocks in its rpc anyway.
                # Indefinitely-blocking ops get a dedicated thread (like
                # ClientServer._serve): dedicated actor workers spawn
                # beyond num_workers, so a bounded pool could fill with
                # blocked get/wait calls and deadlock the put/submit
                # that would unblock them.
                if msg[2] in ("get", "wait"):
                    threading.Thread(
                        target=self._handle_worker_msg, args=(h, msg),
                        daemon=True,
                        name=f"ray_tpu_remote_rpc_w{h.worker_num}").start()
                else:
                    self._rpc_pool.submit(self._handle_worker_msg, h, msg)
            else:
                self._handle_worker_msg(h, msg)

    def _kill_handle(self, h: _Handle) -> None:
        self._send_daemon(("kill", h.worker_num))

    def pids(self) -> List[int]:
        pids = self._ping()
        return sorted(pids.values()) if pids else []

    def live_process_count(self) -> int:
        pids = self._ping()
        return len(pids) if pids else 0

    def _ping(self, timeout: float = 2.0) -> Optional[Dict[int, int]]:
        if self._conn_dead:
            return None
        pid_ = self._next_req()
        ev: threading.Event = threading.Event()
        slot: list = []
        self._pings[pid_] = (ev, slot)
        if self._conn_dead:
            # registered after _on_daemon_lost swept the table: bail now
            # instead of waiting out the timeout
            self._pings.pop(pid_, None)
            return None
        self._send_daemon(("ping", pid_))
        if not ev.wait(timeout) or not slot:
            self._pings.pop(pid_, None)
            return None
        return slot[0]

    def simulate_machine_death(self) -> None:
        """Chaos: SIGKILL the node daemon AND its whole worker tree
        (the daemon runs in its own session — see the
        start_new_session spawn flag — so killpg takes out every
        process on the 'machine' at once; nothing survives to flush an
        outbox or report a death). The control plane is NOT told; the
        severed connection / health checks must notice."""
        import signal

        self._respawn_disabled = True
        if self._daemon_proc is not None:
            pid = self._daemon_proc.pid
            killed = False
            try:
                # only a daemon in its OWN process group is tree-
                # killable; a same-group daemon (legacy spawn) falls
                # back to killing just the daemon process
                if os.getpgid(pid) != os.getpgid(0):
                    os.killpg(os.getpgid(pid), signal.SIGKILL)
                    killed = True
            except (OSError, ProcessLookupError):
                pass
            if not killed:
                try:
                    self._daemon_proc.kill()
                except Exception:
                    pass
        else:
            self._send_daemon(("exit",))

    def take_local_tids(self) -> set:
        """Node-death reconciliation: claim (snapshot + clear) the
        locally-admitted in-flight lease set, so the reconciler — not
        the worker-failure sweep — decides each lease's fate exactly
        once."""
        with self._seq_lock:
            tids, self._local_tids = self._local_tids, set()
        return tids

    # -- object movement ----------------------------------------------
    def fetch_object(self, oid: ObjectID,
                     timeout: Optional[float] = None) -> Optional[bytes]:
        """Pull an object's framed bytes out of the node's arena/spill
        tier (the PullManager request). The timeout guards against a
        hung daemon, not a slow transfer — default is config-driven so
        multi-GB objects don't misreport as lost."""
        if self._conn_dead:
            return None
        if timeout is None:
            from ray_tpu._private.config import GLOBAL_CONFIG
            timeout = GLOBAL_CONFIG.object_transfer_timeout_s
        fid = self._next_req()
        ev: threading.Event = threading.Event()
        slot: list = []
        self._fetches[fid] = (ev, slot)
        if self._conn_dead:
            # registered after _on_daemon_lost swept the table: bail now
            # instead of waiting out the transfer timeout
            self._fetches.pop(fid, None)
            return None
        self._send_daemon(("fetch", fid, oid.binary()))
        if not ev.wait(timeout) or not slot or not slot[0]:
            self._fetches.pop(fid, None)
            return None
        data = slot[1]
        # the chaos transfer fault mutates the RAW received bytes HERE,
        # before any consumer sees them — the frame-completeness check
        # (worker.fetch_object_bytes) must observe the injected
        # truncation, never a pristine buffer with the fault applied
        # downstream of the check
        fault = self._chaos.poll("transfer", node=self.node_index,
                                 object=oid.hex()[:16])
        if fault is not None and data:
            keep = max(1, int(len(data) * fault.get("keep_fraction", 0.5)))
            data = data[:keep]
        if data:
            # head-mediated fetches are cross-node traffic too: count
            # them so bytes-saved accounting reconciles against the
            # total arg bytes moved
            self._worker.note_transfer("bytes_pulled", len(data))
        return data

    def free_remote(self, oids: List[ObjectID]) -> None:
        self._send_daemon(("free", [o.binary() for o in oids]))

    def stage_args(self, entries: List[tuple]) -> None:
        """Dispatch-time staging: (oid_bin, peer_address, nbytes)
        triples the daemon's pull manager starts fetching NOW, while
        the lease waits in the worker queue. Fire-and-forget — a lost
        or failed pull just means the exec-time localization path pays
        the transfer as before."""
        self._send_daemon(("stage", entries))

    # -- log plane queries ---------------------------------------------
    def _log_request(self, msg_tail: tuple,
                     timeout: float) -> Optional[list]:
        """One request/reply round-trip on the daemon link (same slot
        idiom as fetch_object/_ping)."""
        if self._conn_dead:
            return None
        rid = self._next_req()
        ev: threading.Event = threading.Event()
        slot: list = []
        self._logreqs[rid] = (ev, slot)
        if self._conn_dead:
            self._logreqs.pop(rid, None)
            return None
        self._send_daemon((msg_tail[0], rid) + msg_tail[1:])
        if not ev.wait(timeout) or not slot:
            self._logreqs.pop(rid, None)
            return None
        return slot

    def list_logs_remote(self, timeout: float = 5.0) -> List[dict]:
        """{filename, size_bytes, mtime} rows from the node's log dir."""
        slot = self._log_request(("log_list",), timeout)
        return slot[0] if slot else []

    def fetch_log_remote(self, filename: str, tail: Optional[int] = None,
                         timeout: float = 5.0) -> str:
        """Read a capture file off the node. Raises on daemon-side
        errors (bad filename, missing file) and unreachable daemons."""
        slot = self._log_request(("log_read", filename, tail), timeout)
        if slot is None:
            raise rex.NodeDiedError(
                f"node {self.node_id.hex()[:16]} unreachable for log read")
        ok, text = slot
        if not ok:
            raise FileNotFoundError(text)
        return text

    def _resolve_for_ship(self, v: Any) -> Any:
        if not isinstance(v, ObjectRef):
            return v
        oid = v.object_id()
        locs = self._worker.gcs.object_locations(oid)
        if self.node_index in locs:
            # already resident in the target node's arena (primary OR a
            # staged secondary copy): the worker reads it zero-copy
            # through its daemon (no wire bytes)
            return _PullValue(oid.binary())
        if any(self._worker.peer_address_of(n) is not None
               for n in locs):
            # resident on a THIRD node with a peer endpoint: ship the
            # pull marker — the worker's get flows daemon -> head,
            # whose reply directs a direct peer pull (bytes travel
            # producer node -> consumer node, never through the head)
            return _PullValue(oid.binary())
        entry = self._worker.memory_store.get_entry(oid)
        if entry is None:
            if self._worker.object_recovery.maybe_recover(oid):
                raise _RequeueDeps([oid])
            entry = self._worker.memory_store.get_entry(oid)
        if entry is None:
            raise _DepError(rex.ObjectLostError(oid.hex()))
        if entry.is_exception:
            raise _DepError(entry.value)
        # resolves head-arena placeholders, spilled restores, AND
        # third-node RemotePlaceholders (head-mediated fetch), then
        # embeds the value in the payload — the actual DCN transfer
        return self._worker._entry_value(oid, entry)

    def store_result_entries(self, return_ids: List[ObjectID],
                             entries: list) -> None:
        for oid, entry in zip(return_ids, entries):
            if entry[0] == "remote_shm":
                # size recorded so locality scoring / staging know the
                # arg bytes without a cross-node round trip
                self._worker.memory_store.put(
                    oid, RemotePlaceholder(self.node_index),
                    size=int(entry[1] or 0))
                self._worker.gcs.object_location_add(oid, self.node_index)
            else:
                from ray_tpu._private.serialization import (SerializedObject,
                                                            deserialize)
                value = deserialize(SerializedObject.from_bytes(entry[1]))
                self._worker.memory_store.put(oid, value)
            self._worker.scheduler.notify_object_ready(oid)

    # -- worker-initiated RPC overrides --------------------------------
    def _rpc_put(self, h: _Handle, oid_bin: bytes, loc: tuple) -> bool:
        if loc[0] != "remote_shm":
            return super()._rpc_put(h, oid_bin, loc)
        oid = ObjectID(oid_bin)
        self._worker.reference_counter.add_owned_object(oid)
        self._worker.reference_counter.add_borrower(oid, h.worker_id)
        self._task_borrows(h).add(oid)
        self._worker.memory_store.put(oid, RemotePlaceholder(self.node_index),
                                      size=int(loc[1] or 0))
        self._worker.gcs.object_location_add(oid, self.node_index)
        self._worker.scheduler.notify_object_ready(oid)
        return True

    def _rpc_get(self, h: _Handle, oid_bins: list,
                 timeout: Optional[float]) -> list:
        oids = [ObjectID(b) for b in oid_bins]
        try:
            entries = self._worker.memory_store.wait_and_get(oids, timeout)
        except TimeoutError as e:
            raise rex.GetTimeoutError(str(e)) from None
        out = []
        for oid, entry in zip(oids, entries):
            if entry.is_exception:
                out.append(("exc", cloudpickle.dumps(entry.value)))
                continue
            value = entry.value
            if isinstance(value, RemotePlaceholder):
                locs = self._worker.gcs.object_locations(oid)
                if value.node_index not in locs:
                    locs.append(value.node_index)
                if self.node_index in locs:
                    # resident on the REQUESTING node (primary or a
                    # staged secondary): daemon rewrites this to a
                    # zero-copy arena location
                    out.append(("node_shm", oid.binary()))
                    continue
                peer = next(
                    (p for p in (self._worker.peer_address_of(n)
                                 for n in locs) if p is not None), None)
                if peer is not None:
                    # DIRECT node-to-node pull: reply with the
                    # producer's peer endpoint; the consuming daemon
                    # fetches the bytes itself — they never cross the
                    # head (reference: ObjectManager pull protocol)
                    out.append(("peer", oid.binary(), peer))
                    continue
                data = self._worker.fetch_object_bytes(oid,
                                                       value.node_index)
                if data is None:
                    out.append(("exc", cloudpickle.dumps(
                        rex.ObjectLostError(oid.hex()))))
                else:
                    out.append(("inline", data))
                continue
            from ray_tpu._private.runtime.process_pool import ShmPlaceholder
            if isinstance(value, ShmPlaceholder):
                sobj = self._worker.shm_store.get_serialized(oid)
                if sobj is None:
                    out.append(("exc", cloudpickle.dumps(
                        rex.ObjectLostError(oid.hex()))))
                else:
                    out.append(("inline", sobj.to_bytes()))
            else:
                out.append(("inline", serialize(value).to_bytes()))
        return out

    def fail_node(self, reason: str) -> None:
        super().fail_node(reason)
        self._send_daemon(("exit",))

    # -- shutdown ------------------------------------------------------
    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._queue.clear()
            self._idle.clear()
        self._send_daemon(("exit",))
        try:
            with self._conn_lock:
                self._conn.close()
        except Exception:
            pass
        if self._daemon_proc is not None:
            try:
                self._daemon_proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                self._daemon_proc.kill()
        self._unlink_dead_arena()
        self._rpc_pool.shutdown(wait=False)


class HeadServer:
    """The head's TCP registration endpoint: node daemons (and later
    remote clients) dial in with an HMAC handshake and a token issued
    at spawn time (reference: the GCS server's listening port that
    raylets register against)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 authkey: Optional[bytes] = None):
        self.authkey = authkey or os.urandom(16)
        self._listener = Listener((host, port), authkey=self.authkey)
        self.address: Tuple[str, int] = self._listener.address
        self._pending: Dict[str, Tuple[threading.Event, list]] = {}
        self._lock = threading.Lock()
        self._closed = False
        self.on_unsolicited = None  # hook for client/CLI registrations
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="ray_tpu_head_accept").start()

    def expect(self, token: str) -> Tuple[threading.Event, list]:
        slot: Tuple[threading.Event, list] = (threading.Event(), [])
        with self._lock:
            self._pending[token] = slot
        return slot

    def issue_token(self) -> str:
        return uuid.uuid4().hex

    def _accept_loop(self) -> None:
        from multiprocessing import AuthenticationError

        while not self._closed:
            try:
                conn = self._listener.accept()
            except AuthenticationError:
                continue  # port-scan / bad-key dial must not kill accepts
            except (OSError, EOFError):
                # mid-handshake death of ONE dialer (peer hung up inside
                # deliver_challenge) must not kill the accept loop — that
                # would leave the whole cluster unreachable (later dials
                # complete TCP against the backlog, then hang in auth
                # forever). Only a closed listener ends the loop.
                if self._closed:
                    return
                time.sleep(0.01)  # if the LISTENER broke, don't spin hot
                continue
            try:
                # bound the hello wait: the accept loop is single-threaded,
                # so one authenticated-but-silent peer would block every
                # later registration behind it
                if not conn.poll(10.0):
                    conn.close()
                    continue
                hello = conn.recv()
            except (EOFError, OSError):
                conn.close()
                continue
            from ray_tpu._private import protocol

            ver, fields = protocol.split_any_hello(hello)
            if not fields:
                conn.close()
                continue
            if ver != protocol.PROTOCOL_VERSION:
                # version skew: reject LOUDLY so the dialer sees why,
                # instead of dying later on a message-shape mismatch
                try:
                    conn.send(protocol.mismatch_error("head", ver))
                except (OSError, ValueError):
                    pass
                conn.close()
                continue
            # downstream parsers see the unversioned layout
            hello = ("hello",) + fields
            token = hello[1]
            with self._lock:
                slot = self._pending.pop(token, None)
            if slot is not None:
                slot[1][:] = [conn, hello]
                slot[0].set()
            elif self.on_unsolicited is not None:
                try:
                    self.on_unsolicited(conn, hello)
                except Exception:
                    logger.exception("unsolicited registration failed")
                    conn.close()
            else:
                conn.close()

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except Exception:
            pass
