"""Process worker pool — driver side of the multi-process node runtime.

Reference surfaces: ray src/ray/raylet/worker_pool.cc (WorkerPool:
prestarted worker processes, PopWorker/PushWorker), the dispatch half of
src/ray/raylet/local_task_manager.cc (a scheduler decision becomes a
lease grant to a worker process), and the owner side of
src/ray/core_worker/ (results stored under the owner's ids, borrower
bookkeeping for refs that cross the process boundary).

Data plane: small values cross the task pipe inline; large values go
through the node's shm arena (create/seal RPC, zero-copy reads) — the
plasma split. Control plane: one duplex pipe per worker for tasks + RPC,
a second for cancellation.
"""

from __future__ import annotations

import collections
import io
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from multiprocessing.connection import Listener
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

import cloudpickle

from ray_tpu import exceptions as rex
from ray_tpu._private import log_plane, spawn_env
from ray_tpu._private.analysis import runtime_sanitizer
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private import trace_plane
from ray_tpu._private.ids import ObjectID, TaskID, WorkerID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.runtime.shm_store import (
    RING_TAG_BYTE as _RING_TAG_BYTE, RING_TAGS as _RING_TAGS, ControlRing)
from ray_tpu._private.runtime.worker_process import _ShmValue, fn_id_of
from ray_tpu._private.scheduler.base import PendingTask
from ray_tpu._private.serialization import (
    NONE_FRAMED, SerializedObject, decode_completion_envelope,
    deserialize, serialize)
from ray_tpu._private.task_spec import (
    EMPTY_ARGS_BLOB, TaskSpec, encode_task_envelope)

logger = logging.getLogger(__name__)


class ShmPlaceholder:
    """Memory-store entry whose bytes live in the shm arena; resolved
    (deserialized zero-copy) on first driver-side access."""

    __slots__ = ()


class RemotePlaceholder:
    """Memory-store entry whose bytes live in a REMOTE node's arena
    (see runtime/remote_pool.py); the GCS object directory records
    which node. Resolved head-side by fetching on first access."""

    __slots__ = ("node_index",)

    def __init__(self, node_index: int):
        self.node_index = node_index


_PLACEHOLDER = ShmPlaceholder()


def auto_pipeline_depth(num_workers: int) -> int:
    """Lease-pipeline depth for a pool of num_workers processes: the
    configured value, or (auto) the worker/core oversubscription ratio
    capped at 8 — 1 on hosts with a core per worker."""
    depth = GLOBAL_CONFIG.worker_pipeline_depth
    if depth <= 0:
        depth = max(1, min(8, -(-num_workers // (os.cpu_count() or 1))))
    return depth


class _RefCollectPickler(cloudpickle.Pickler):
    """cloudpickle that records every ObjectRef crossing the boundary so
    the owner can register borrows (reference: ReferenceCounter borrower
    protocol, src/ray/core_worker/reference_count.cc)."""

    def __init__(self, file, contained: List[ObjectRef]):
        super().__init__(file, protocol=5)
        self._contained = contained

    def reducer_override(self, obj):
        if isinstance(obj, ObjectRef):
            self._contained.append(obj)
        return super().reducer_override(obj)


def _dumps_collect_refs(value: Any) -> Tuple[bytes, List[ObjectRef]]:
    contained: List[ObjectRef] = []
    f = io.BytesIO()
    _RefCollectPickler(f, contained).dump(value)
    return f.getvalue(), contained


class _InFlight:
    """One task leased onto a worker's pipe (the worker executes its
    pipe FIFO; several may be in flight per worker — the reference's
    lease pipelining, ray: NormalTaskSubmitter max_tasks_in_flight)."""

    __slots__ = ("pending", "return_ids", "borrows", "started_at")

    def __init__(self, pending: Optional[PendingTask],
                 return_ids: List[ObjectID]):
        # pending=None marks a SYNTHETIC entry: a lease adopted from a
        # rejoining daemon after head failover. The restarted head's
        # scheduler/task_manager never saw the task, so completion
        # handling for these stores results and frees the worker but
        # must skip every scheduler-side notification.
        self.pending = pending
        self.return_ids = return_ids
        self.borrows: Set[ObjectID] = set()
        self.started_at = time.monotonic()


class _Handle:
    __slots__ = ("worker_num", "proc", "conn", "ctrl", "worker_id", "pid",
                 "inflight", "borrows",
                 "sent_fns", "sent_hdrs", "dead", "force_cancel_id",
                 "timeout_cancel_id", "preempt_cancel_id",
                 "chaos_kill", "send_lock",
                 "ready", "actor_rt", "oom_kill", "log_paths",
                 "ring_in", "ring_out", "ring_region")

    def __init__(self, worker_num: int):
        self.actor_rt = None  # set for dedicated actor workers
        self.worker_num = worker_num
        self.proc: Optional[subprocess.Popen] = None
        self.conn = None
        self.ctrl = None
        self.worker_id = WorkerID.from_random()
        self.pid: Optional[int] = None
        # exec task_id -> _InFlight, in send (= execution) order
        self.inflight: "collections.OrderedDict[TaskID, _InFlight]" = \
            collections.OrderedDict()
        self.oom_kill = False         # memory monitor killed this worker
        self.borrows: Set[ObjectID] = set()  # actor-runtime bookkeeping
        self.sent_fns: Set[bytes] = set()
        # lease-envelope header dedupe: (fn_id, name, num_returns) ->
        # small int id the worker caches the pickled header under
        self.sent_hdrs: Dict[tuple, int] = {}
        # shm control rings (local pools with control_ring on): task
        # ring owner->worker, completion ring worker->owner, plus the
        # (offset, nbytes) pairs to hand back to the arena free list
        self.ring_in: Optional[ControlRing] = None
        self.ring_out: Optional[ControlRing] = None
        self.ring_region: Optional[Tuple[Tuple[int, int], ...]] = None
        self.dead = False
        self.force_cancel_id: Optional[TaskID] = None
        # deadline enforcement killed this worker for this task: the
        # target fails with TaskTimeoutError (retriable), not cancelled
        self.timeout_cancel_id: Optional[TaskID] = None
        # QoS preemption killed this worker for this task: the target
        # fails as a synthetic worker death (retriable WorkerCrashedError
        # carrying the preemption message), never cancelled
        self.preempt_cancel_id: Optional[TaskID] = None
        self.chaos_kill = False       # chaos plane SIGKILLed this worker
        self.send_lock = threading.Lock()
        self.ready = False
        # (out_path, err_path) of the capture files, when the session
        # log dir exists — used to attach a crash's .err tail
        self.log_paths: Optional[Tuple[str, str]] = None


class ProcessWorkerPool:
    is_remote = False
    # head_wall - node_wall at handshake; local pools share the head's
    # clock. RemoteNodePool overwrites this from the daemon's "clock"
    # message so worker execution windows land on the head's time axis.
    clock_offset = 0.0

    def __init__(self, worker, num_workers: int, shm_store,
                 node_index: int = 0):
        self._worker = worker
        self._shm = shm_store
        self.node_index = node_index   # scheduler row this pool serves
        self._node_dead = False        # node died: fail, don't respawn
        self._respawn_disabled = False  # chaos: machine gone, no self-heal
        self._lock = threading.Lock()
        self._idle: Deque[_Handle] = collections.deque()
        self._queue: Deque[Tuple[PendingTask, dict]] = collections.deque()
        self._handles: List[_Handle] = []
        self._actor_handles: List[_Handle] = []
        self._by_num: Dict[int, _Handle] = {}
        self._by_task: Dict[TaskID, _Handle] = {}
        self._shutdown = False
        self._worker_seq = 0
        self._inline_max = GLOBAL_CONFIG.inline_object_max_bytes
        # fault injection routes through the seeded controller, polled
        # PER TASK at payload build (the former per-pool snapshot of
        # testing_inject_task_failure_prob went stale immediately: a
        # probability set after pool construction was never observed)
        from ray_tpu._private.chaos import get_controller
        self._chaos = get_controller()
        # shared-memory control ring (local pools only; remote pools
        # get the same batched-envelope trims over their framed daemon
        # link — the daemon decodes a bookkeeping copy — via
        # RemoteNodePool._assign_many's ("env", ...) path)
        self._ring_on = bool(GLOBAL_CONFIG.control_ring) \
            and not self.is_remote
        self._ring_slots = int(GLOBAL_CONFIG.control_ring_slots)
        self._ring_slot_bytes = int(GLOBAL_CONFIG.control_ring_slot_bytes)
        # control-plane counters exported as the
        # ray_tpu_control_ring_* metric families; plain ints bumped
        # under each handle's send lock (msgs/bytes/full_waits) or the
        # demux thread (drained completions), schema-stable zeros when
        # the ring is off
        self.ring_stats = {"msgs": 0, "bytes": 0, "fallback": 0,
                           "full_waits": 0}
        # per-reason spillback counters (LocalScheduler declines routed
        # through _rpc_submit); keyed by the daemon's reason string,
        # surfaced per node by state.list_nodes
        self.spill_reasons: Dict[str, int] = {}
        # pool-level pickle cache for envelope invariant headers
        self._hdr_blobs: Dict[tuple, bytes] = {}
        # lease pipelining (reference: NormalTaskSubmitter
        # max_tasks_in_flight_per_worker + ReportWorkerBacklog): several
        # tasks ride one worker pipe so a wakeup executes a batch. Depth
        # auto-scales with core oversubscription — on hosts with >= one
        # core per worker it stays 1 (pure spread, lowest latency); on
        # small hosts packing beats fake parallelism.
        self._pipeline_depth = auto_pipeline_depth(num_workers)
        # children exec `python -m ...worker_process` and dial back here
        # (reference: raylet execs default_worker.py; registration over a
        # unix socket) — never fork/spawn of this process, whose jax/TPU
        # state and threads are not fork-safe and whose __main__ must not
        # be re-run
        self._start_transport()
        for _ in range(num_workers):
            self._handles.append(self._spawn())

    def _start_transport(self) -> None:
        """Local transport: a unix socket the exec'd workers dial back
        to (remote pools talk to a node daemon instead). ONE demux
        thread multiplexes every worker pipe (connection.wait) instead
        of a reader thread per worker: on small hosts the per-task
        thread ping-pong, not the pipe itself, is the dominant cost,
        and a single drain point lets completions batch into one
        scheduler wakeup (the reference's lease-return batching)."""
        import socket

        self._authkey = os.urandom(16)
        self._sock_dir = tempfile.mkdtemp(prefix="ray_tpu_pool_")
        self._listener = Listener(
            address=os.path.join(self._sock_dir, "pool.sock"),
            family="AF_UNIX", authkey=self._authkey)
        self._demux_conns: Dict[Any, _Handle] = {}
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="ray_tpu_pool_accept").start()
        threading.Thread(target=self._demux_loop, daemon=True,
                         name=f"ray_tpu_pool_demux_{self.node_index}"
                         ).start()

    # ------------------------------------------------------------------
    # worker lifecycle
    # ------------------------------------------------------------------
    def _spawn(self) -> _Handle:
        with self._lock:
            self._worker_seq += 1
            num = self._worker_seq
        h = _Handle(num)
        with self._lock:
            self._by_num[num] = h
        # the HEAD owns the accelerator (single-chip lease; same stance
        # as the reference's GPU ownership via resources) — worker
        # processes skip the site-level TPU plugin bootstrap, which
        # costs seconds of import, a device-lease fight, and (with a
        # degraded tunnel) an indefinite hang at `import jax`
        extra = {"RAY_TPU_AUTHKEY": self._authkey.hex()}
        if GLOBAL_CONFIG.profile_hz > 0:
            # the owner may have been configured via _system_config (no
            # env var) — re-export so the fresh interpreter's GLOBAL_CONFIG
            # starts its profile sampler
            extra["RAY_TPU_PROFILE_HZ"] = str(GLOBAL_CONFIG.profile_hz)
        log_dir = log_plane.get_session_log_dir()
        if log_dir:
            stem = f"worker-{h.worker_id.hex()[:12]}"
            log_env = log_plane.child_log_env(
                log_dir, stem, GLOBAL_CONFIG.log_rotation_bytes,
                GLOBAL_CONFIG.log_rotation_backups)
            h.log_paths = (log_env[log_plane.ENV_LOG_OUT],
                           log_env[log_plane.ENV_LOG_ERR])
            extra.update(log_env)
        env = spawn_env.child_env(
            use_accelerator=GLOBAL_CONFIG.worker_tpu_access,
            inherit_sys_path=True,
            extra=extra)
        ring_arg = "-"
        if self._ring_on:
            ring_arg = self._alloc_rings(h)
        h.proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.runtime.worker_process",
             self._listener.address, self._shm.arena.name,
             str(self._inline_max), str(num), ring_arg],
            env=env, close_fds=True)
        h.pid = h.proc.pid
        threading.Thread(target=self._monitor_proc, args=(h,), daemon=True,
                         name=f"ray_tpu_pool_monitor_{num}").start()
        return h

    def _alloc_rings(self, h: _Handle) -> str:
        """Carve this worker's pair of control rings out of the shm
        arena; returns the geometry argv token the child attaches with
        ("-" = no rings, pipe-only — e.g. the arena has no room)."""
        from ray_tpu._private.object_store import ObjectStoreFullError

        arena = self._shm.arena
        nslots, sbytes = self._ring_slots, self._ring_slot_bytes
        rb = ControlRing.region_bytes(nslots, sbytes)
        try:
            off_in = arena.allocate(rb)
        except ObjectStoreFullError:
            return "-"
        try:
            off_out = arena.allocate(rb)
        except ObjectStoreFullError:
            arena.free(off_in, rb)
            return "-"
        h.ring_in = ControlRing(arena, off_in, nslots, sbytes, create=True)
        h.ring_out = ControlRing(arena, off_out, nslots, sbytes, create=True)
        h.ring_region = ((off_in, rb), (off_out, rb))
        return f"{off_in}:{off_out}:{nslots}:{sbytes}"

    def _free_rings(self, h: _Handle) -> None:
        """Return a dead/released worker's ring regions to the arena.
        Detach under the send lock so a racing producer (executor
        thread) or the demux drain never touches freed memory; a
        respawned replacement gets fresh zeroed rings."""
        with h.send_lock:
            rings = (h.ring_in, h.ring_out)
            region = h.ring_region
            h.ring_in = h.ring_out = h.ring_region = None
        for r in rings:
            if r is not None:
                r.close()
        if region is not None:
            for off, rb in region:
                try:
                    self._shm.arena.free(off, rb)
                except Exception:
                    pass  # arena already shut down

    def _monitor_proc(self, h: _Handle) -> None:
        h.proc.wait()
        self._on_worker_failure(h, f"exit code {h.proc.returncode}")

    @staticmethod
    def _err_tail(h: _Handle) -> str:
        """Last lines of the dead worker's .err capture — the actual
        crash traceback — appended to WorkerCrashedError messages so
        the real cause surfaces instead of just "worker died"."""
        if h.log_paths is None:
            return ""
        return log_plane.err_tail_message(h.log_paths[1])

    def _accept_loop(self) -> None:
        from multiprocessing import AuthenticationError

        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except AuthenticationError:
                continue  # a stale/foreign dialer must not kill accepts
            except (OSError, EOFError):
                return
            try:
                hello = conn.recv()
            except (EOFError, OSError):
                conn.close()
                continue
            from ray_tpu._private import protocol

            ver, fields = protocol.split_any_hello(hello)
            if len(fields) != 2:
                conn.close()
                continue
            if ver != protocol.PROTOCOL_VERSION:
                try:
                    conn.send(protocol.mismatch_error("worker pool", ver))
                except (OSError, ValueError):
                    pass
                conn.close()
                continue
            num, kind = fields
            with self._lock:
                h = self._by_num.get(num)
            if h is None or h.dead:
                conn.close()
                continue
            if kind == "task":
                h.conn = conn
                self._demux_conns[conn] = h
                try:
                    self._wake_w.send(b"w")
                except OSError:
                    pass
            else:
                h.ctrl = conn

    def pids(self) -> List[int]:
        with self._lock:
            return [h.pid for h in self._handles if h.pid is not None]

    def live_process_count(self) -> int:
        """Workers whose OS process is still running (health checks)."""
        with self._lock:
            handles = list(self._handles) + list(self._actor_handles)
        n = 0
        for h in handles:
            if h.proc is not None and h.proc.poll() is None:
                n += 1
        return n

    def simulate_machine_death(self) -> None:
        """Chaos helper: the machine is gone — workers die and the pool
        cannot self-heal (a lone worker crash respawns a replacement; a
        dead machine cannot). The control plane is NOT told; the GCS
        health checker must detect it."""
        self._respawn_disabled = True
        with self._lock:
            handles = list(self._handles) + list(self._actor_handles)
        for h in handles:
            self._kill_handle(h)

    def fail_node(self, reason: str) -> None:
        """The node this pool backs died: fail queued work retriably, kill
        every worker process, and stop respawning replacements (the
        monitors' _on_worker_failure handles each running task). Actor
        workers get killed too; their runtimes observe _on_process_died
        and restart on another node or go DEAD."""
        with self._lock:
            if self._node_dead:
                return
            self._node_dead = True
            queued = list(self._queue)
            self._queue.clear()
            handles = list(self._handles) + list(self._actor_handles)
        for pending, payload in queued:
            spec = pending.spec
            return_ids = [ObjectID(b) for b in payload["return_ids"]]
            exc = rex.NodeDiedError(
                f"node died before task {spec.name} started: {reason}")
            retry = self._worker._handle_task_failure(spec, return_ids, exc)
            self._finish_task(pending, spec.task_id, retry)
        for h in handles:
            self._kill_handle(h)
            if h.actor_rt is not None:
                # a REMOTE pool has no per-process monitor to observe
                # that kill — the daemon that would report worker_died
                # died with the node — so synthesize the failure here
                # or the actor runtime never learns its process is
                # gone (no restart, in-flight rounds hang). Idempotent
                # under _on_worker_failure's was_dead guard, so the
                # local-pool monitor double-firing is harmless.
                self._on_worker_failure(h, rex.NodeDiedError(
                    f"node died: {reason}"))

    def _kill_handle(self, h: _Handle) -> None:
        """SIGKILL the worker behind a handle (remote pools route this
        through the node daemon)."""
        if h.proc is not None:
            try:
                h.proc.kill()
            except Exception:
                pass

    # ------------------------------------------------------------------
    # dedicated actor workers (reference: every actor gets its own
    # worker process; GcsActorScheduler leases one at creation)
    # ------------------------------------------------------------------
    def spawn_actor_worker(self, actor_rt) -> _Handle:
        h = self._spawn()
        h.actor_rt = actor_rt
        with self._lock:
            self._actor_handles.append(h)
        return h

    def send_to(self, h: _Handle, msg: tuple) -> None:
        with h.send_lock:
            h.conn.send(msg)

    def release_actor_worker(self, h: _Handle, kill: bool = False) -> None:
        with self._lock:
            try:
                self._actor_handles.remove(h)
            except ValueError:
                pass
            h.dead = True
            self._by_num.pop(h.worker_num, None)
        if kill:
            self._kill_handle(h)
        elif h.conn is not None:
            try:
                with h.send_lock:
                    h.conn.send(("exit",))
            except (OSError, ValueError):
                pass

    # ------------------------------------------------------------------
    # submission (called from the driver's dispatch thread pool)
    # ------------------------------------------------------------------
    def run_task(self, pending: PendingTask) -> None:
        payload = self._prepare_payload(pending)
        if payload is None:
            return
        with self._lock:
            if self._shutdown:
                return
            h = self._pick_worker_locked()
            if h is None:
                self._queue.append((pending, payload))
                return
        self._assign(h, pending, payload)

    def run_task_batch(self, pendings: List[PendingTask]) -> None:
        """One tick's lease grants for this node in one pass: payloads
        build back to back, each worker receives ALL its tasks in a
        single pipe message (one wakeup, one preemption — the per-send
        context switch was the dominant cost of the one-at-a-time
        path on oversubscribed hosts)."""
        built: List[tuple] = []  # (pending, payload)
        for pending in pendings:
            payload = self._prepare_payload(pending)
            if payload is not None:
                built.append((pending, payload))
        if not built:
            return
        per_handle: Dict[_Handle, list] = {}
        provisional: Dict[_Handle, int] = {}
        with self._lock:
            if self._shutdown:
                return
            for pending, payload in built:
                # Picks within one batch must see each other: inflight
                # counts only update in _assign_many, so without the
                # provisional map every post-idle task would land on the
                # same "least-loaded" worker and blow the depth invariant.
                h = self._pick_worker_locked(provisional)
                if h is None:
                    self._queue.append((pending, payload))
                else:
                    per_handle.setdefault(h, []).append((pending, payload))
                    provisional[h] = provisional.get(h, 0) + 1
        for h, items in per_handle.items():
            self._assign_many(h, items)

    def _prepare_payload(self, pending: PendingTask) -> Optional[dict]:
        """run_task's build/error half: a payload ready to lease, or
        None if the task already resolved to an error/requeue."""
        spec = pending.spec
        exec_task_id = spec.task_id
        return_ids = (getattr(spec, "_retry_return_ids", None)
                      or spec.return_ids())
        if self._node_dead:
            exc = rex.NodeDiedError(
                f"task {spec.name} dispatched to a dead node")
            retry = self._worker._handle_task_failure(spec, return_ids, exc)
            self._finish_task(pending, exec_task_id, retry)
            return None
        try:
            return self._build_payload(spec, return_ids)[0]
        except _RequeueDeps as e:
            from ray_tpu._private.worker import _top_level_deps

            self._worker.reference_counter.add_submitted_task_references(
                _top_level_deps(spec.args, spec.kwargs))
            self._finish_task(pending, exec_task_id,
                              PendingTask(spec=spec, deps=list(e.oids),
                                          execute=lambda t, n: None))
            return None
        except _DepError as e:
            self._worker._store_error(spec, return_ids, e.error)
            self._finish_task(pending, exec_task_id, None)
            return None
        except Exception as e:  # unserializable task
            self._worker._store_error(
                spec, return_ids,
                rex.TaskError(spec.name, e, "task serialization failed"))
            self._finish_task(pending, exec_task_id, None)
            return None

    def _assign_many(self, h: _Handle, items: List[tuple]) -> None:
        """Lease a run of tasks onto one worker with ONE pipe write."""
        if self._ring_on:
            self._assign_many_ring(h, items)
            return
        out = []
        for pending, payload in items:
            spec = pending.spec
            contained = payload.pop("_contained")
            inf = _InFlight(pending,
                            [ObjectID(b) for b in payload["return_ids"]])
            h.oom_kill = False
            for oid in contained:
                self._worker.reference_counter.add_borrower(oid, h.worker_id)
                inf.borrows.add(oid)
            with self._lock:
                h.inflight[spec.task_id] = inf
                self._by_task[spec.task_id] = h
            self._worker.events.record(spec.task_id, spec.name, "started",
                                       self.node_index)
            out.append(payload)
        for pending, _payload in items:
            if self._chaos_assign(h, pending.spec):
                return  # killed or dropped: inflight recovers retriably
        try:
            with h.send_lock:
                # fn-blob strip under the send lock (see _assign)
                for i, payload in enumerate(out):
                    if payload["fn_id"] in h.sent_fns:
                        out[i] = dict(payload, fn_blob=None)
                    else:
                        h.sent_fns.add(payload["fn_id"])
                h.conn.send(("tasks", out))
        except (OSError, ValueError) as e:
            self._on_worker_failure(h, e)

    def _assign_many_ring(self, h: _Handle, items: List[tuple]) -> None:
        """Envelope variant of _assign_many: the tick's leases for this
        worker pack into ONE struct-framed envelope on the shm ring
        (pipe doorbell after; framed pipe send as fallback).

        Tasks are grouped by invariant header first and all owner-side
        bookkeeping runs in that grouped order — the worker executes
        the envelope front to back, and the inflight FIFO must match
        execution order (a worker RPC's borrow attaches to the OLDEST
        inflight lease)."""
        groups: Dict[tuple, list] = {}
        for pending, payload in items:
            groups.setdefault(
                (payload["fn_id"], payload["name"],
                 payload["num_returns"]), []).append((pending, payload))
        infs = []
        for pairs in groups.values():
            for pending, payload in pairs:
                contained = payload.pop("_contained")
                inf = _InFlight(pending,
                                [ObjectID(b)
                                 for b in payload["return_ids"]])
                for oid in contained:
                    self._worker.reference_counter.add_borrower(
                        oid, h.worker_id)
                    inf.borrows.add(oid)
                infs.append((pending.spec.task_id, inf))
        h.oom_kill = False
        with self._lock:
            for tid, inf in infs:
                h.inflight[tid] = inf
                self._by_task[tid] = h
        self._worker.events.record_batch(
            [(p.spec.task_id, p.spec.name)
             for pairs in groups.values() for p, _ in pairs],
            "started", self.node_index)
        if self._chaos.armed():
            for pairs in groups.values():
                for pending, _payload in pairs:
                    if self._chaos_assign(h, pending.spec):
                        return  # killed/dropped: inflight recovers
        try:
            with h.send_lock:
                blob = encode_task_envelope(
                    [(key, [p for _, p in pairs])
                     for key, pairs in groups.items()],
                    h.sent_fns, h.sent_hdrs, self._hdr_blobs)
                self._ring_send(("env", blob), h)
        except (OSError, ValueError) as e:
            self._on_worker_failure(h, e)

    def _ring_send(self, msg: tuple, h: _Handle) -> None:
        """Ship one control message to the worker: ring slot + pipe
        doorbell when it fits, framed pipe message otherwise. Caller
        holds h.send_lock — the ring is strictly single-producer, and
        the doorbell-after-put ordering is what keeps ring traffic
        FIFO-consistent with everything else on the pipe."""
        ring = h.ring_in
        stats = self.ring_stats
        if ring is not None:
            data = _RING_TAG_BYTE[msg[0]] + msg[1]
            if len(data) <= ring.max_msg:
                if ring.try_put(data):
                    stats["msgs"] += 1
                    stats["bytes"] += len(data)
                    h.conn.send(("ring",))
                    return
                stats["full_waits"] += 1
        stats["fallback"] += 1
        h.conn.send(msg)

    def _pick_worker_locked(
            self, provisional: Optional[Dict["_Handle", int]] = None,
    ) -> Optional[_Handle]:
        """Lease target for one task: an IDLE worker first (true
        process concurrency — tasks that sleep or block must overlap),
        then, at depth > 1, the least-loaded busy worker with pipe room
        (the backlog pipelines instead of round-tripping the
        scheduler). `provisional` counts picks made earlier in the same
        batch that haven't reached the handles' inflight sets yet."""
        if self._idle:
            return self._idle.popleft()
        if self._pipeline_depth <= 1:
            return None
        # while a worker is still booting, QUEUE instead of pipelining
        # onto an already-busy sibling: its ready message parks it via
        # _mark_idle, which drains the queue — piling up early would
        # serialize a burst onto the first worker to come up (the
        # envelope transport made first-task latency shorter than
        # worker startup, so this window is routinely hit now)
        for h in self._handles:
            if not h.dead and not h.ready and h.actor_rt is None:
                return None
        best = None
        best_n = self._pipeline_depth
        for h in self._handles:
            if h.dead or not h.ready or h.actor_rt is not None:
                continue
            n = len(h.inflight)
            if provisional:
                n += provisional.get(h, 0)
            # n == 0 here means a FREE worker that simply hasn't been
            # re-parked in _idle yet (completion handling re-parks after
            # popping inflight) — it must win over piling a second task
            # onto a busy handle, or a burst submitted right as the
            # previous one completes serializes onto one process
            if n < best_n:
                best, best_n = h, n
                if n == 0:
                    break
        return best

    def _build_payload(self, spec: TaskSpec,
                       return_ids: List[ObjectID]) -> Tuple[dict, list]:
        if not spec.args and not spec.kwargs:
            # the dominant high-rate shape (fan-outs of no-arg tasks)
            # skips the pickler entirely; the shared constant also lets
            # the envelope encoder elide the blob by identity
            args_blob, contained = EMPTY_ARGS_BLOB, []
        else:
            args = tuple(self._resolve_for_ship(a) for a in spec.args)
            kwargs = {k: self._resolve_for_ship(v)
                      for k, v in spec.kwargs.items()}
            args_blob, contained = _dumps_collect_refs((args, kwargs))
        fn_blob = spec.serialized_func
        fn_id = spec.func_id
        if fn_blob is None:
            fn_blob = cloudpickle.dumps(spec.func)
            fn_id = fn_id_of(fn_blob)
        elif fn_id is None:
            # specs built from retained lease records (failover / node
            # loss resubmits) carry the original blob but no cached id;
            # a None id would collide every such fn in the per-worker
            # fn_cache and the sent_fns dedupe, executing the WRONG
            # function body under this task's name
            fn_id = fn_id_of(fn_blob)
        payload = dict(
            task_id=spec.task_id.binary(),
            name=spec.name,
            fn_id=fn_id,
            fn_blob=fn_blob,
            args_blob=args_blob,
            num_returns=spec.num_returns,
            return_ids=[o.binary() for o in return_ids],
            # attempt token: daemons echo it in rejoin reports so a head
            # restarted mid-run can tell a live lease from a stale replay
            # of an attempt it already resubmitted (failover exactly-once)
            attempt=spec.attempt_number,
        )
        tctx = getattr(spec, "trace_ctx", None)
        if tctx is not None and tctx[3]:
            # trace context rides the payload dict (no new wire tag);
            # the worker restores it around exec so nested submissions
            # inherit parentage
            payload["trace"] = tctx
            if GLOBAL_CONFIG.trace_log_markers:
                payload["trace_mark"] = True
        fault = self._chaos.poll("task", node=self.node_index,
                                 task=spec.name)
        if fault is not None:
            payload["inject_fault"] = fault["kind"]
            if fault["kind"] == "hang":
                payload["inject_hang_s"] = fault.get("hang_s", 0.2)
        if spec.placement_group_id is not None \
                and spec.placement_group_capture_child_tasks:
            # capture context crosses the process boundary so nested
            # .remote() calls inherit the group (thread mode uses a
            # contextvar in Worker._execute_task)
            payload["pg"] = spec.placement_group_id.binary()
        env_vars = (spec.runtime_env or {}).get("env_vars") or {}
        if env_vars:
            payload["env_vars"] = dict(env_vars)
        renv = spec.runtime_env or {}
        if renv.get("working_dir_pkg"):
            payload["working_dir_pkg"] = renv["working_dir_pkg"]
        if renv.get("pip"):
            payload["pip"] = list(renv["pip"])
        payload["_contained"] = [r.object_id() for r in contained]
        return payload, contained

    def _resolve_for_ship(self, v: Any) -> Any:
        """Top-level ObjectRef -> value (small) or _ShmValue (large)."""
        if not isinstance(v, ObjectRef):
            return v
        oid = v.object_id()
        loc = self._shm.locate(oid)
        if loc is not None:
            return _ShmValue(*loc)
        entry = self._worker.memory_store.get_entry(oid)
        if entry is None:
            # lost since scheduling: reconstruct from lineage; the task
            # re-queues behind the recovery instead of failing
            if self._worker.object_recovery.maybe_recover(oid):
                raise _RequeueDeps([oid])
            entry = self._worker.memory_store.get_entry(oid)
        if entry is None:
            raise _DepError(rex.ObjectLostError(oid.hex()))
        if entry.is_exception:
            raise _DepError(entry.value)
        if isinstance(entry.value, (ShmPlaceholder, RemotePlaceholder)):
            # not in this node's arena: SPILLED to disk (restore) or
            # resident on a remote node (head-mediated fetch) — either
            # way _entry_value materializes it to ship by value
            return self._worker._entry_value(oid, entry)
        return entry.value

    def _chaos_assign(self, h: _Handle, spec: TaskSpec) -> bool:
        """Chaos sites on the lease path: ``worker`` (SIGKILL the
        assigned worker; everything inflight on it fails retriably) and
        ``link`` (delay or drop the dispatch message). True = the
        message must not be sent."""
        fault = self._chaos.poll("worker", node=self.node_index,
                                 task=spec.name)
        if fault is not None:
            h.chaos_kill = True
            self._kill_handle(h)
            return True
        fault = self._chaos.poll("link", node=self.node_index,
                                 task=spec.name)
        if fault is not None:
            if fault["kind"] == "drop":
                # message lost on the wire: the lease hangs until a
                # deadline or node-death path recovers it
                return True
            time.sleep(fault.get("delay_s", 0.05))
        return False

    def _assign(self, h: _Handle, pending: PendingTask, payload: dict) -> None:
        if self._ring_on:
            # singles ride the same envelope/ring path as batches: one
            # transport, one set of dedupe caches, one wire schema
            self._assign_many_ring(h, [(pending, payload)])
            return
        spec = pending.spec
        contained = payload.pop("_contained")
        inf = _InFlight(pending, [ObjectID(b) for b in payload["return_ids"]])
        h.oom_kill = False   # stale flag must not mislabel later deaths
        # register borrows for refs crossing into the worker BEFORE the
        # task can observe them
        for oid in contained:
            self._worker.reference_counter.add_borrower(oid, h.worker_id)
            inf.borrows.add(oid)
        with self._lock:
            h.inflight[spec.task_id] = inf
            self._by_task[spec.task_id] = h
        self._worker.events.record(spec.task_id, spec.name, "started",
                                   self.node_index)
        if self._chaos_assign(h, spec):
            return
        try:
            # fn-blob strip decided under the SEND lock: sends to one
            # handle serialize here, so check-then-strip cannot race a
            # concurrent sender into shipping fn_blob=None first
            with h.send_lock:
                if payload["fn_id"] in h.sent_fns:
                    payload = dict(payload, fn_blob=None)
                else:
                    h.sent_fns.add(payload["fn_id"])
                h.conn.send(("task", payload))
        except (OSError, ValueError) as e:
            self._on_worker_failure(h, e)

    # ------------------------------------------------------------------
    # reader: completions + worker-initiated RPC
    # ------------------------------------------------------------------
    def _demux_loop(self) -> None:
        """Single reader over all worker pipes. Completions found in one
        wait cycle batch into one result-store pass + one scheduler
        wakeup. Blocking worker RPCs (get/wait) jump to their own
        thread — a worker issuing one is itself blocked, so per-worker
        ordering holds; everything else is handled inline."""
        from multiprocessing.connection import wait as _conn_wait

        while not self._shutdown:
            conns = list(self._demux_conns)
            try:
                ready = _conn_wait([self._wake_r] + conns, timeout=0.5)
            except OSError:
                ready = []  # a conn died under wait; next pass drops it
            dones: List[tuple] = []
            for c in ready:
                if c is self._wake_r:
                    try:
                        self._wake_r.recv(4096)
                    except (BlockingIOError, OSError):
                        pass
                    continue
                h = self._demux_conns.get(c)
                if h is None:
                    continue
                while True:
                    try:
                        msg = c.recv()
                    except (EOFError, OSError):
                        self._demux_conns.pop(c, None)
                        self._on_worker_failure(h, None)
                        break
                    runtime_sanitizer.check_wire("worker_to_owner", msg)
                    kind = msg[0]
                    if kind == "many":
                        # a worker's buffered batch completions; the
                        # dominant shape is all-"done" 4-tuples, which
                        # extracts in ONE batched pass (the former
                        # per-sub tail probe with its repeated length
                        # guards was measurable at high completion
                        # rates) — anything mixed takes the slow path
                        subs = msg[1]
                        if h.actor_rt is None and all(
                                s[0] == "done" and len(s) == 4
                                for s in subs):
                            dones.extend(
                                (h, TaskID(s[1]), s[2], s[3])
                                for s in subs)
                        else:
                            for sub in subs:
                                if sub[0] == "done" \
                                        and h.actor_rt is None:
                                    dones.append(
                                        (h, TaskID(sub[1]), sub[2],
                                         sub[3] if len(sub) > 3
                                         else None))
                                else:
                                    dones = self._flush_dones_safe(dones)
                                    self._handle_worker_msg(h, sub)
                    elif kind == "done" and h.actor_rt is None:
                        dones.append((h, TaskID(msg[1]), msg[2],
                                      msg[3] if len(msg) > 3 else None))
                    elif kind == "cring":
                        # completion-ring doorbell: drain the worker's
                        # shm ring (envelopes decode outside any lock)
                        dones = self._drain_comp_ring(h, dones)
                    else:
                        # per-worker message order is a protocol
                        # invariant (e.g. an rpc_put's borrow attaches
                        # to the OLDEST inflight lease): flush buffered
                        # completions before any other message
                        dones = self._flush_dones_safe(dones)
                        if kind == "rpc" and msg[2] in ("get", "wait"):
                            threading.Thread(
                                target=self._handle_worker_msg,
                                args=(h, msg), daemon=True,
                                name=f"ray_tpu_pool_rpc_w{h.worker_num}"
                            ).start()
                        else:
                            self._handle_worker_msg(h, msg)
                    try:
                        if not c.poll(0):
                            break
                    except (OSError, ValueError):
                        break
            self._flush_dones_safe(dones)

    def _flush_dones_safe(self, dones: List[tuple]) -> List[tuple]:
        """Process buffered completions; the demux thread must survive
        any single bad completion (a dead demux hangs the whole pool)."""
        if dones:
            try:
                self._on_done_batch(dones)
            except Exception:
                logger.exception("batched completion handling failed")
        return []

    def _drain_comp_ring(self, h: _Handle,
                         dones: List[tuple]) -> List[tuple]:
        """Pop every envelope off one worker's completion ring. The
        byte copies happen under the handle's send lock (so _free_rings
        can never pull the region out from under us); decode and
        completion handling run unlocked."""
        with h.send_lock:
            ring = h.ring_out
            msgs = ring.drain() if ring is not None else ()
        if msgs:
            stats = self.ring_stats
            stats["msgs"] += len(msgs)
            stats["bytes"] += sum(len(m) for m in msgs)
        for data in msgs:
            tag = _RING_TAGS.get(data[0])
            if tag is None:
                logger.error("unknown ring tag %d from worker %d",
                             data[0], h.worker_num)
                continue
            msg = (tag, bytes(memoryview(data)[1:]))
            runtime_sanitizer.check_wire("worker_to_owner", msg)
            dones = self._handle_ring_msg(h, msg, dones)
        return dones

    def _handle_ring_msg(self, h: _Handle, msg: tuple,
                         dones: List[tuple]) -> List[tuple]:
        """Dispatch one reconstructed ring message (same tag/arity
        discipline as the pipe: raylint's wire pass checks this handler
        against the ring send sites)."""
        kind = msg[0]
        if kind == "cenv":
            for item in decode_completion_envelope(msg[1]):
                if item[0] == "done" and h.actor_rt is None:
                    dones.append((h, TaskID(item[1]), item[2], item[3]))
                else:
                    # errors keep the completions-before-anything-else
                    # ordering invariant, exactly like the pipe path
                    dones = self._flush_dones_safe(dones)
                    self._handle_worker_msg(h, item)
        return dones

    def _handle_worker_msg(self, h: _Handle, msg: tuple) -> None:
        """One worker->owner message (shared by the local per-worker
        reader threads and the remote node demux)."""
        kind = msg[0]
        try:
            if kind == "ready":
                h.pid = msg[1]
                h.ready = True
                if h.actor_rt is not None:
                    h.actor_rt._on_worker_ready(h)
                else:
                    self._mark_idle(h)
            elif kind == "done":
                if h.actor_rt is not None:
                    h.actor_rt._on_remote_done(
                        TaskID(msg[1]), msg[2],
                        msg[3] if len(msg) > 3 else None)
                else:
                    self._on_done(h, TaskID(msg[1]), msg[2],
                                  msg[3] if len(msg) > 3 else None)
            elif kind == "err":
                if h.actor_rt is not None:
                    h.actor_rt._on_remote_err(TaskID(msg[1]), msg[2],
                                              msg[3])
                else:
                    self._on_err(h, TaskID(msg[1]), msg[2], msg[3],
                                 msg[4] if len(msg) > 4 else None)
            elif kind == "rpc":
                self._on_rpc(h, msg[1], msg[2], msg[3])
            elif kind == "prof":
                # folded-stack batch from the worker's profile sampler;
                # shared branch covers local pipes AND daemon-forwarded
                # ("w", ...) reports from remote workers
                pp = getattr(self._worker, "profile_plane", None)
                if pp is not None:
                    pp.record_batch(self.node_index, msg[1])
        except Exception:
            logger.exception("pool reader failed handling %s", kind)

    def _mark_idle(self, h: _Handle) -> None:
        """Worker has pipe room: feed it from the queue or park it."""
        nxt = None
        with self._lock:
            if self._shutdown or h.dead:
                return
            if self._queue:
                nxt = self._queue.popleft()
            elif not h.inflight and h not in self._idle:
                self._idle.append(h)
        if nxt is not None:
            self._assign(h, *nxt)

    def _lease_done(self, task_id: TaskID) -> None:
        """Hook: a leased attempt reached a terminal state on this
        pool. RemoteNodePool journals it for failover reconciliation;
        local pools have nothing to reconcile."""

    def _take_inflight(self, h: _Handle, task_id: TaskID):
        """Claim a completion/error: pop the inflight entry AND the
        task index under the pool lock, so a concurrent
        _on_worker_failure (monitor/tick threads) can never
        double-handle the task as both completed and crashed. Returns
        None when someone else (force-cancel, failure path) already
        claimed it."""
        with self._lock:
            inf = h.inflight.pop(task_id, None)
            self._by_task.pop(task_id, None)
        return inf

    def _release_taken(self, h: _Handle, inf) -> None:
        """Post-claim half of _release for entries already popped by
        _take_inflight."""
        for oid in inf.borrows:
            self._worker.reference_counter.remove_borrower(
                oid, h.worker_id)
        self._mark_idle(h)

    def _store_entries(self, return_ids: List[ObjectID],
                       entries: list) -> List[ObjectID]:
        """Seal + register worker-produced result locations under the
        owner's ids (shm entries resolve lazily; inline deserialized).
        Returns the stored oids; the CALLER notifies the scheduler."""
        for oid, entry in zip(return_ids, entries):
            if entry[0] == "shm":
                self._shm.seal(oid)
                self._worker.memory_store.put(oid, _PLACEHOLDER)
            else:
                data = entry[1]
                if data == NONE_FRAMED:
                    # precomputed no-result frame: skip the pickler
                    self._worker.memory_store.put(oid, None)
                else:
                    value = deserialize(
                        SerializedObject.from_bytes(data))
                    self._worker.memory_store.put(oid, value)
        return return_ids

    def store_result_entries(self, return_ids: List[ObjectID],
                             entries: list) -> None:
        for oid in self._store_entries(return_ids, entries):
            self._worker.scheduler.notify_object_ready(oid)

    def _on_done(self, h: _Handle, task_id: TaskID, entries: list,
                 timing=None) -> None:
        inf = self._take_inflight(h, task_id)
        if inf is None:
            # force-cancel/worker-failure claimed the task first — or,
            # on a FENCED pool (node rejoined after being declared
            # dead), this is a dead-era lease's late completion: the
            # reconciler already resubmitted it, so the stale result is
            # dropped, never double-resolved
            if getattr(self, "_fenced", False):
                self._worker.note_two_level("orphan_fenced")
            return
        if inf.pending is None:
            # adopted lease (failover re-attach or node-local
            # dispatch): resolve the refs, free the worker. The trace
            # plane may hold a live record for it (local-dispatch
            # lane); unknown ids are a no-op pop there. Pin release
            # keeps the record as lineage — this is the REMOTE node's
            # completion path, and the returns may be the sole copy in
            # that node's arena
            self._worker.release_local_lease_pins(task_id.binary(),
                                                  keep_lineage=True)
            self.store_result_entries(inf.return_ids, entries)
            tp = self._worker.trace_plane
            if tp is not None:
                tp.record_finished_batch(
                    ((task_id, timing, h.worker_id.hex(),
                      self.node_index),), offset=self.clock_offset)
            self._lease_done(task_id)
            self._release_taken(h, inf)
            return
        pending, spec = inf.pending, inf.pending.spec
        self.store_result_entries(inf.return_ids, entries)
        self._worker.task_manager.complete(spec.task_id)
        te = self._worker.task_events
        if te is not None:
            te.record_finished_batch(
                ((task_id, timing, h.worker_id.hex(), self.node_index),),
                offset=self.clock_offset)
        tp = self._worker.trace_plane
        if tp is not None:
            tp.record_finished_batch(
                ((task_id, timing, h.worker_id.hex(), self.node_index),),
                offset=self.clock_offset)
        self._finish_task(pending, task_id, None)
        self._release_taken(h, inf)

    def _on_done_batch(self, dones: List[tuple]) -> None:
        """N completions -> one store pass, release/requeue per worker,
        then ONE scheduler wakeup (object-ready and task-finished
        events delivered together via notify_batch). The
        inflight entry is POPPED under the pool lock up front so a
        concurrent _on_worker_failure (monitor/tick threads) can never
        double-handle a task as both completed and crashed."""
        from ray_tpu._private.worker import _top_level_deps

        ready_oids: List[ObjectID] = []
        finished: List[tuple] = []
        taken: List[tuple] = []
        events = self._worker.events
        te = self._worker.task_events
        tp = self._worker.trace_plane
        te_rows: List[tuple] = []
        with self._lock:
            for h, task_id, entries, timing in dones:
                inf = h.inflight.pop(task_id, None)
                if inf is None:
                    continue  # force-cancel/failure raced the completion
                self._by_task.pop(task_id, None)
                taken.append((h, task_id, entries, timing, inf))
        for h, task_id, entries, timing, inf in taken:
            self._lease_done(task_id)
            if inf.pending is None:
                # adopted lease (failover re-attach or node-local
                # dispatch): store results only (no spec, no
                # scheduler/task-manager state for this task here).
                # keep_lineage: the record becomes the lineage entry
                # that reconstructs sole-copy returns after node death
                self._worker.release_local_lease_pins(task_id.binary(),
                                                      keep_lineage=True)
                try:
                    ready_oids.extend(
                        self._store_entries(inf.return_ids, entries))
                    if tp is not None:
                        tp.record_finished_batch(
                            ((task_id, timing, h.worker_id.hex(),
                              self.node_index),),
                            offset=self.clock_offset)
                except Exception:
                    logger.exception("adopted-lease completion failed")
                continue
            spec = inf.pending.spec
            try:
                ready_oids.extend(
                    self._store_entries(inf.return_ids, entries))
                self._worker.task_manager.complete(spec.task_id)
                events.record(task_id, spec.name, "finished",
                              self.node_index)
                if te is not None or tp is not None:
                    te_rows.append((task_id, timing, h.worker_id.hex(),
                                    self.node_index))
                deps = _top_level_deps(spec.args, spec.kwargs)
                if deps:
                    self._worker.reference_counter \
                        .remove_submitted_task_references(deps)
            except Exception:
                logger.exception("completion handling failed for %s",
                                 spec.name)
            finished.append((task_id, inf.pending.node_index,
                             spec.resources))
        if te_rows:
            if te is not None:
                te.record_finished_batch(te_rows,
                                         offset=self.clock_offset)
            if tp is not None:
                tp.record_finished_batch(te_rows,
                                         offset=self.clock_offset)
        # park/refeed the workers BEFORE waking the scheduler: a driver
        # blocked in get() resumes the moment notify_batch lands, and if
        # it submits immediately the picker must already see these
        # workers as idle (the ring coalesces a whole burst into one
        # batch, so with notify first NO worker would be parked yet and
        # the next burst would pile onto a single handle)
        for h, task_id, _entries, _timing, inf in taken:
            for oid in inf.borrows:
                self._worker.reference_counter.remove_borrower(
                    oid, h.worker_id)
            self._mark_idle(h)
        self._worker.scheduler.notify_batch(ready_oids, finished)

    def _on_err(self, h: _Handle, task_id: TaskID, exc_blob: bytes,
                tb: str, timing=None) -> None:
        inf = self._take_inflight(h, task_id)
        if inf is None:
            # force-cancel/worker-failure claimed it first — or a
            # fenced pool dropping a dead-era lease's late error (see
            # _on_done)
            if getattr(self, "_fenced", False):
                self._worker.note_two_level("orphan_fenced")
            return
        if inf.pending is None:
            # adopted failover lease: no spec survives the restart, so
            # fail the refs terminally instead of consulting retry policy
            self._worker.release_local_lease_pins(task_id.binary())
            try:
                exc = cloudpickle.loads(exc_blob)
            except Exception:
                exc = RuntimeError(
                    "worker error (exception undeserializable)")
            exc._ray_tpu_traceback = tb
            for oid in inf.return_ids:
                self._worker.memory_store.put(oid, exc, is_exception=True)
                self._worker.scheduler.notify_object_ready(oid)
            tp = self._worker.trace_plane
            if tp is not None:
                tp.record_failed(task_id, type(exc).__name__)
            self._lease_done(task_id)
            self._release_taken(h, inf)
            return
        pending, spec = inf.pending, inf.pending.spec
        try:
            exc = cloudpickle.loads(exc_blob)
        except Exception:
            exc = RuntimeError("worker error (exception undeserializable)")
        exc._ray_tpu_traceback = tb
        te = self._worker.task_events
        if te is not None:
            # attach the execution window before the failure hooks
            # finalize (retry or terminal) this attempt's record
            te.record_exec(task_id, timing, node=self.node_index,
                           worker=h.worker_id.hex(),
                           offset=self.clock_offset)
        tp = self._worker.trace_plane
        if tp is not None:
            tp.record_exec(task_id, timing, node=self.node_index,
                           worker=h.worker_id.hex(),
                           offset=self.clock_offset)
        retry = self._worker._handle_task_failure(spec, inf.return_ids, exc)
        self._finish_task(pending, task_id, retry)
        self._release_taken(h, inf)

    def _finish_task(self, pending: PendingTask, exec_task_id: TaskID,
                     retry: Optional[PendingTask]) -> None:
        from ray_tpu._private.worker import _top_level_deps

        spec = pending.spec
        self._worker.events.record(exec_task_id, spec.name, "finished",
                                   self.node_index)
        deps = _top_level_deps(spec.args, spec.kwargs)
        self._worker.reference_counter.remove_submitted_task_references(deps)
        self._worker.scheduler.notify_task_finished(
            exec_task_id, pending.node_index, spec.resources)
        if retry is not None:
            self._worker._submit_retry(retry)

    def _on_worker_failure(self, h: _Handle, cause) -> None:
        with self._lock:
            if h.dead:
                if h.actor_rt is not None:
                    pass  # released actor workers still notify their rt
                else:
                    return
            was_dead = h.dead
            h.dead = True
            self._by_num.pop(h.worker_num, None)
            try:
                self._idle.remove(h)
            except ValueError:
                pass
            shutting_down = self._shutdown
        if h.actor_rt is not None:
            self._free_rings(h)
            if not shutting_down and not was_dead:
                h.actor_rt._on_process_died(h, cause)
            return
        with self._lock:
            inflight = list(h.inflight.items())
            h.inflight.clear()
        if inflight and not shutting_down:
            # every task leased onto this worker's pipe dies with it;
            # only the force-cancel TARGET gets the cancellation error,
            # innocent pipelined neighbors fail retriably
            for exec_id, inf in inflight:
                if inf.pending is None:
                    # adopted lease (locally dispatched or re-attached
                    # across head failover) with no spec to retry from.
                    # A LIVE daemon re-leases anything with attempts
                    # left itself (its local_retry report moved the
                    # entry off this handle first); whatever reaches
                    # here goes through the head-side orphan-lease
                    # reconciler, which resubmits under the original
                    # return oids when a retained record still carries
                    # attempts (whole-node death, no sibling slot) and
                    # fails the refs terminally otherwise
                    err = rex.WorkerCrashedError(
                        f"worker process {h.pid} died while running an "
                        f"adopted lease (locally dispatched with retries "
                        f"exhausted, or re-attached across head "
                        f"failover): {cause}" + self._err_tail(h))
                    self._worker.reconcile_orphan_lease(
                        exec_id.binary(),
                        [oid.binary() for oid in inf.return_ids], err)
                    self._lease_done(exec_id)
                    with self._lock:
                        self._by_task.pop(exec_id, None)
                    continue
                spec = inf.pending.spec
                if h.force_cancel_id == exec_id:
                    exc: BaseException = rex.TaskCancelledError(exec_id)
                elif h.timeout_cancel_id == exec_id:
                    exc = rex.TaskTimeoutError(
                        f"task {spec.name} exceeded its {spec.timeout_s}s "
                        f"deadline (worker {h.pid} killed)",
                        task_id=exec_id, timeout_s=spec.timeout_s)
                elif h.preempt_cancel_id == exec_id:
                    # synthetic worker death: retriable, so the victim
                    # re-queues with a bumped attempt under its original
                    # return ids — the QoS preemption contract
                    exc = rex.WorkerCrashedError(
                        f"task {spec.name} preempted by higher-tier work "
                        f"(worker {h.pid} killed); attempt will retry")
                elif h.oom_kill:
                    exc = rex.OutOfMemoryError(
                        f"worker killed by the memory monitor while "
                        f"running {spec.name} (host memory pressure)")
                elif self._node_dead:
                    exc = rex.NodeDiedError(
                        f"node died while running {spec.name}")
                elif h.chaos_kill:
                    exc = rex.WorkerCrashedError(
                        f"worker process {h.pid} killed while running "
                        f"{spec.name} (chaos worker kill)"
                        + self._err_tail(h))
                else:
                    exc = rex.WorkerCrashedError(
                        f"worker process {h.pid} died while running "
                        f"{spec.name}: {cause}" + self._err_tail(h))
                retry = self._worker._handle_task_failure(
                    spec, inf.return_ids, exc)
                self._finish_task(inf.pending, exec_id, retry)
                for oid in inf.borrows:
                    self._worker.reference_counter.remove_borrower(
                        oid, h.worker_id)
                with self._lock:
                    self._by_task.pop(exec_id, None)
        self._free_rings(h)
        if not shutting_down and not self._node_dead \
                and not self._respawn_disabled:
            # replacement worker keeps the pool at capacity (with its
            # own fresh rings — _spawn re-initializes the geometry)
            replacement = self._spawn()
            with self._lock:
                try:
                    self._handles[self._handles.index(h)] = replacement
                except ValueError:
                    self._handles.append(replacement)

    # ------------------------------------------------------------------
    # worker-initiated RPC (get/put/submit/create/wait from inside tasks)
    # ------------------------------------------------------------------
    def _on_rpc(self, h: _Handle, req_id: int, op: str, args: tuple) -> None:
        try:
            data = getattr(self, f"_rpc_{op}")(h, *args)
            ok = True
        except BaseException as e:  # noqa: BLE001
            ok, data = False, cloudpickle.dumps(e)
        with h.send_lock:
            h.conn.send(("reply", req_id, ok, data))

    def _rpc_create(self, h: _Handle, oid_bin: bytes, nbytes: int) -> int:
        return self._shm.create(ObjectID(oid_bin), nbytes)

    def _rpc_env_pkg(self, h: _Handle, pkg_hash: str) -> Optional[bytes]:
        """Content-addressed runtime_env package fetch (working_dir
        zips live in the GCS KV; workers cache extractions per node)."""
        from ray_tpu._private import runtime_envs as rte

        return self._worker.gcs.kv_get(rte.kv_key(pkg_hash))

    def _task_borrows(self, h: _Handle) -> Set[ObjectID]:
        """Borrow set of the task EXECUTING on h right now (= oldest
        inflight lease; a worker only issues RPCs mid-execution). Falls
        back to the handle set (dedicated actor workers)."""
        with self._lock:
            if h.inflight:
                return next(iter(h.inflight.values())).borrows
        return h.borrows

    def _rpc_put(self, h: _Handle, oid_bin: bytes, loc: tuple) -> bool:
        oid = ObjectID(oid_bin)
        self._worker.reference_counter.add_owned_object(oid)
        # the worker holds the only handle: track it as a borrower until
        # the task completes (driver-side refs appear if the ref is
        # returned, which deserializes and registers locally first)
        self._worker.reference_counter.add_borrower(oid, h.worker_id)
        self._task_borrows(h).add(oid)
        if loc[0] == "shm":
            self._shm.seal(oid)
            self._worker.memory_store.put(oid, _PLACEHOLDER)
        else:
            value = deserialize(SerializedObject.from_bytes(loc[1]))
            self._worker.memory_store.put(oid, value)
        self._worker.scheduler.notify_object_ready(oid)
        return True

    def _rpc_get(self, h: _Handle, oid_bins: list,
                 timeout: Optional[float]) -> list:
        oids = [ObjectID(b) for b in oid_bins]
        try:
            entries = self._worker.memory_store.wait_and_get(oids, timeout)
        except TimeoutError as e:
            raise rex.GetTimeoutError(str(e)) from None
        out = []
        for oid, entry in zip(oids, entries):
            if entry.is_exception:
                out.append(("exc", cloudpickle.dumps(entry.value)))
                continue
            if isinstance(entry.value, RemotePlaceholder):
                # produced on a remote node: head-mediated pull, shipped
                # inline to this (local) worker
                data = self._worker.fetch_object_bytes(
                    oid, entry.value.node_index)
                if data is None:
                    out.append(("exc", cloudpickle.dumps(
                        rex.ObjectLostError(oid.hex()))))
                else:
                    out.append(("inline", data))
                continue
            loc = self._shm.locate(oid)
            if loc is not None:
                out.append(("shm", loc[0], loc[1]))
            elif isinstance(entry.value, ShmPlaceholder):
                # spilled: the file bytes ARE a framed SerializedObject —
                # ship them as-is instead of deserializing into driver
                # heap (pinning the value) and re-serializing
                sobj = self._shm.get_serialized(oid)
                if sobj is None:
                    out.append(("exc", cloudpickle.dumps(
                        rex.ObjectLostError(oid.hex()))))
                else:
                    out.append(("inline", sobj.to_bytes()))
            else:
                out.append(("inline", serialize(entry.value).to_bytes()))
        return out

    def _rpc_wait(self, h: _Handle, oid_bins: list, num_returns: int,
                  timeout: Optional[float]) -> list:
        oids = [ObjectID(b) for b in oid_bins]
        ready = self._worker.memory_store.wait(oids, num_returns, timeout)
        return [o.binary() for o in oids if o in ready]

    def _rpc_submit(self, h: _Handle, blob: bytes,
                    spilled=False) -> list:
        from ray_tpu._private.ids import PlacementGroupID

        if spilled:
            # the node's LocalScheduler declined this nested submission:
            # upward spillback — the head stays placement authority.
            # `spilled` carries the daemon's reason string (queue_full /
            # pg / resources / refs / no_slot); per-reason counters ride
            # lazily-created "spillback:<reason>" keys so the base
            # stats schema is unchanged with reasons at zero
            reason = spilled if isinstance(spilled, str) else "other"
            self._worker.note_two_level("spillback")
            self._worker.note_two_level("spillback:" + reason)
            self.spill_reasons[reason] = \
                self.spill_reasons.get(reason, 0) + 1
            note = getattr(self._worker.scheduler, "note_spillback", None)
            if note is not None:
                note()
        d = cloudpickle.loads(blob)
        func = cloudpickle.loads(d["func_blob"])
        args, kwargs = cloudpickle.loads(d["args_blob"])
        spec = TaskSpec(
            task_id=self._worker.next_task_id(),
            name=d["name"],
            func=func,
            func_descriptor=d["func_descriptor"],
            args=args,
            kwargs=kwargs,
            num_returns=d["num_returns"],
            resources=d["resources"],
            max_retries=d["max_retries"],
            retry_exceptions=d["retry_exceptions"],
            placement_group_id=(PlacementGroupID(d["pg_id"])
                                if d.get("pg_id") is not None else None),
            placement_group_bundle_index=d.get("pg_bundle_index", -1),
            placement_group_capture_child_tasks=d.get("pg_capture", False),
            priority=int(d.get("priority") or 0),
            tenant=d.get("tenant") or "default",
        )
        # the submitting task's trace context rides the RPC blob: the
        # nested submission becomes its child via the ambient parent
        with trace_plane.parent_scope(d.get("trace")):
            refs = self._worker.submit_task(spec)
        borrows = self._task_borrows(h)
        for r in refs:
            self._worker.reference_counter.add_borrower(
                r.object_id(), h.worker_id)
            borrows.add(r.object_id())
        return [r.object_id().binary() for r in refs]

    def _rpc_actor_call(self, h: _Handle, blob: bytes,
                        meta: Optional[tuple] = None) -> list:
        """Actor method submitted from INSIDE a worker-process task
        (reference: core-worker actor task submission from any worker).
        Runs the normal head-side submission path; the caller's task
        borrows the return refs until it completes. ``meta`` is the
        p2p routing hint the node daemon intercepts — by the time the
        call reaches the head it has already chosen the head path, so
        the hint is ignored here."""
        from ray_tpu._private.ids import ActorID
        from ray_tpu.actor import ActorHandle

        t = cloudpickle.loads(blob)
        aid_bin, method, args, kwargs, num_returns = t[:5]
        tctx = t[5] if len(t) > 5 else None
        handle = ActorHandle(ActorID(aid_bin))
        with trace_plane.parent_scope(tctx):
            out = getattr(handle, method).options(
                num_returns=num_returns).remote(*args, **kwargs)
        refs = out if isinstance(out, list) else [out]
        borrows = self._task_borrows(h)
        for r in refs:
            self._worker.reference_counter.add_borrower(
                r.object_id(), h.worker_id)
            borrows.add(r.object_id())
        return [r.object_id().binary() for r in refs]

    # ------------------------------------------------------------------
    # cancellation
    # ------------------------------------------------------------------
    def cancel(self, task_id: TaskID, force: bool) -> bool:
        # not yet leased to a worker: drop it from the pool queue and
        # resolve its return refs with the cancellation error
        with self._lock:
            for item in self._queue:
                if item[0].spec.task_id == task_id:
                    self._queue.remove(item)
                    queued = item[0]
                    break
            else:
                queued = None
        if queued is not None:
            spec = queued.spec
            err = rex.TaskCancelledError(task_id)
            return_ids = (getattr(spec, "_retry_return_ids", None)
                          or spec.return_ids())
            self._worker._store_error(spec, return_ids, err)
            self._finish_task(queued, task_id, None)
            return True
        with self._lock:
            h = self._by_task.get(task_id)
        if h is None:
            return False
        if force:
            h.force_cancel_id = task_id
            self._kill_handle(h)
        elif h.ctrl is not None:
            try:
                h.ctrl.send(("cancel", task_id.binary()))
            except (OSError, ValueError):
                pass
        return True

    def cancel_for_timeout(self, task_id: TaskID) -> bool:
        """Deadline enforcement: fail the attempt with a retriable
        TaskTimeoutError — cancel()'s force path with a different
        classification (the timeout counts against max_retries instead
        of resolving the refs as cancelled)."""
        with self._lock:
            for item in self._queue:
                if item[0].spec.task_id == task_id:
                    self._queue.remove(item)
                    queued = item[0]
                    break
            else:
                queued = None
        if queued is not None:
            spec = queued.spec
            return_ids = (getattr(spec, "_retry_return_ids", None)
                          or spec.return_ids())
            err = rex.TaskTimeoutError(
                f"task {spec.name} timed out after {spec.timeout_s}s "
                f"queued on node {self.node_index}",
                task_id=task_id, timeout_s=spec.timeout_s)
            retry = self._worker._handle_task_failure(spec, return_ids, err)
            self._finish_task(queued, task_id, retry)
            return True
        with self._lock:
            h = self._by_task.get(task_id)
        if h is None:
            return False
        h.timeout_cancel_id = task_id
        self._kill_handle(h)
        return True

    def cancel_for_preemption(self, task_id: TaskID) -> bool:
        """QoS preemption (config.qos): fail the attempt as a synthetic
        worker death — cancel_for_timeout's shape with a retriable
        WorkerCrashedError classification, so the victim re-queues with
        a bumped attempt under its original return ids and the
        journaled-lease dedup guarantees exactly-once effects."""
        with self._lock:
            for item in self._queue:
                if item[0].spec.task_id == task_id:
                    self._queue.remove(item)
                    queued = item[0]
                    break
            else:
                queued = None
        if queued is not None:
            spec = queued.spec
            return_ids = (getattr(spec, "_retry_return_ids", None)
                          or spec.return_ids())
            err = rex.WorkerCrashedError(
                f"task {spec.name} preempted by higher-tier work while "
                f"queued on node {self.node_index}; attempt will retry")
            retry = self._worker._handle_task_failure(spec, return_ids, err)
            self._finish_task(queued, task_id, retry)
            return True
        with self._lock:
            h = self._by_task.get(task_id)
        if h is None:
            return False
        h.preempt_cancel_id = task_id
        self._kill_handle(h)
        return True

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            handles = list(self._handles) + list(self._actor_handles)
            self._queue.clear()
            self._idle.clear()
        for h in handles:
            if h.conn is not None:
                try:
                    with h.send_lock:
                        h.conn.send(("exit",))
                except (OSError, ValueError):
                    pass
        for h in handles:
            if h.proc is not None:
                try:
                    h.proc.wait(timeout=1.0)
                except subprocess.TimeoutExpired:
                    h.proc.kill()
        for h in handles:
            self._free_rings(h)
            for c in (h.conn, h.ctrl):
                if c is not None:
                    try:
                        c.close()
                    except Exception:
                        pass
        try:
            self._listener.close()
        except Exception:
            pass
        try:
            self._wake_w.send(b"q")  # unblock the demux wait promptly
        except OSError:
            pass
        try:
            os.rmdir(self._sock_dir)
        except OSError:
            pass


class _DepError(Exception):
    def __init__(self, error: BaseException):
        self.error = error


class _RequeueDeps(Exception):
    """Deps lost but reconstructing: re-queue the task behind them."""

    def __init__(self, oids):
        self.oids = oids
