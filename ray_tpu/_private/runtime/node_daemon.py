"""Node daemon — the raylet-process analog for remote (off-head) nodes.

Reference surfaces: ray src/ray/raylet/ (the per-node raylet binary:
owns the node's plasma store and worker pool, talks to the GCS/head over
the network) and src/ray/object_manager/ (the node-local half of object
transfer). The reference speaks gRPC over the DCN; here the head link is
one authenticated (HMAC) framed-message TCP connection
(multiprocessing.connection over AF_INET) — localhost stands in for the
DCN in tests, and the protocol is transport-agnostic: every message is a
small picklable tuple, object BYTES ride the same link only when they
actually cross nodes.

The daemon is a *multiplexer with a local object store*:

  - it execs and monitors this node's worker processes (the same
    worker_process.py used on the head's local nodes), each attached to
    the DAEMON's own shm arena — per-node object planes, like one
    plasma store per node;
  - worker messages are forwarded to the head tagged with the worker
    number, and head messages are routed to the right worker pipe, so
    the head-side pool logic (leases, retries, borrows, actor protocol)
    is identical for local and remote nodes;
  - it INTERCEPTS the object-plane RPCs it can serve node-locally:
    `create` allocates in the local arena, `get` is answered with
    zero-copy arena locations when every requested object is already
    sealed here, and sealed task returns are rewritten to compact
    ``("remote_shm", nbytes)`` markers so result bytes never cross the
    wire until someone actually needs them (locality: results stay
    where they were produced, as in the reference's object manager);
  - it serves the head's transfer ops: ``fetch`` (read object bytes out
    of the arena/spill tier for a cross-node consumer) and ``free``.

Head -> daemon messages:
  ("spawn", num[, wid_hex])   exec a worker process numbered `num`
                              (wid_hex names its log capture files)
  ("to_w", num, msg)          deliver msg on worker num's task pipe
  ("to_ctrl", num, msg)       deliver msg on worker num's control pipe
  ("kill", num)               SIGKILL worker num (force-cancel path)
  ("fetch", fid, oid_bin)     -> ("fetched", fid, ok, bytes)
  ("stage", [(oid_bin, peer_address, nbytes), ...])
                              dispatch-time arg staging: start peer
                              pulls of these objects NOW (task-arg
                              priority) so the transfer overlaps the
                              lease's queue wait
  ("free", [oid_bin, ...])    drop objects from the local store
  ("ping", pid_)              -> ("pong", pid_, {num: pid})
  ("log_list", rid)           -> ("log_listed", rid, rows)
  ("log_read", rid, filename, tail)
                              -> ("log_data", rid, ok, text_or_error)
  ("resview", view)           two-level dispatch push: {accept, p2p,
                              cap, job, chaos, v, peers, resident} —
                              refreshed view gating the daemon's LOCAL
                              submission queue and advertising the p2p
                              actor lane (plus a mirror of the head's
                              armed chaos plan). `v` is a monotonic
                              version for peer gossip tiebreaks,
                              `peers` the other nodes' peer addresses,
                              `resident` a digest (8-byte oid
                              prefixes) of this node's object-
                              directory residency so ref-carrying
                              submissions can admit locally
  ("aroute", aid_bin, route)  actor-route reply for an ("aresolve",
                              aid_bin) request: (node_index, address,
                              worker_num) or None
  ("node_dead", info)         route invalidation: a PEER node died
                              (info: {index, peer}); evict its gossip
                              view, drop cached p2p actor routes to
                              its address and sweep in-flight lane
                              calls to the head path NOW instead of
                              waiting out the p2p result timeout
  ("fence", epoch)            this daemon rejoined AFTER the head
                              declared its node dead: clear dead-era
                              local-lease / in-flight-p2p / outbox
                              state — the head already resubmitted or
                              failed everything that era produced, so
                              a zombie re-lease or stale fallback
                              would double-execute
  ("exit",)                   kill workers and exit

Daemon -> head messages:
  ("w", num, msg)             message from worker num (maybe rewritten)
  ("worker_died", num, code[, err_tail])
                              worker process exited (err_tail: last
                              lines of its .err capture, or "")
  ("fetched", fid, ok, data)  fetch reply
  ("pong", pid_, pids)        ping reply
  ("log", fname, lines)       appended log lines from a capture file
                              (unsolicited; the head's LogMonitor
                              re-emits them on the driver)
  ("pulled", oid_bin)         a peer pull (staged or exec-time) landed
                              the object in this node's store; the
                              head registers a SECONDARY copy in the
                              object directory
  ("log_listed", rid, rows)   log_list reply
  ("log_data", rid, ok, text) log_read reply
  ("local_lease", tid, info)  the LocalScheduler admitted a worker
                              submission against the head-pushed
                              resource view and leased it to a sibling
                              worker; info carries everything the head
                              needs to journal the lease (fn/args
                              blobs, return ids, attempt, max_retries)
  ("local_retry", tid, info)  a locally-dispatched lease's worker died
                              and the daemon re-leased the SAME task
                              (same return oids, attempt+1) to a
                              sibling worker without a head
                              round-trip; the head moves its adopted
                              in-flight entry to the new worker and
                              re-journals the bumped attempt token
                              (FIFO-ordered before the worker_died
                              report, which then skips the moved
                              lease)
  ("p2p_done", tid, info)     completion receipt for a peer-dispatched
                              actor call EXECUTED on this node: result
                              entries + timing for lineage/ref-counts
                              (the only head traffic a p2p call costs)
  ("p2p_fallback", tid, info) a p2p call this node ORIGINATED could
                              not complete over the peer lane; the
                              head re-runs it with the same task id +
                              attempt token (worker-side dedup makes
                              the retry exactly-once)
  ("aresolve", aid_bin)       actor-route request -> ("aroute", ...)
  ("fault", entry)            a mirrored chaos injection fired on this
                              daemon (e.g. peer_link); joins the
                              head's injection log/counters

Report-class messages (w / worker_died / pulled / log / local_lease /
p2p_done / p2p_fallback / fault — anything the
head must not lose across a blackout) don't travel bare: they ride a
sequence-numbered outbox envelope ("seq", n, depth, is_replay, inner)
and are buffered until the head acknowledges them with ("ack", n)
(high-water mark; the daemon trims its outbox prefix). After a link
drop the daemon replays every unacked entry on rejoin; the head dedups
by per-node sequence number, so a transient flap delivers each report
exactly once. Request/reply tags (fetched/pong/log_listed/log_data)
and the clock handshake stay bare — their requester died with the old
link, so replaying them is meaningless.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import threading
import time
from multiprocessing.connection import Client, Listener
from typing import Any, Dict, Optional

from ray_tpu._private.analysis import runtime_sanitizer
from ray_tpu._private.analysis.runtime_checks import assert_holds
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID


class _Outbox:
    """Sequence-numbered buffer of report-class daemon->head messages.

    Every message appended gets the next sequence number and stays
    buffered until the head acks a high-water mark at or past it
    (``ack`` trims the prefix). While the head link is down nothing is
    lost — ``pending()`` snapshots the unacked tail for replay after a
    rejoin. Depth is bounded in practice by the rejoin timeout times
    the node's report rate; an explicit cap would silently violate the
    exactly-once contract, so there isn't one.
    """

    def __init__(self):
        import collections

        self._entries = collections.deque()   # (seq, msg), seq ascending
        self._next_seq = 1
        self._lock = threading.Lock()

    def append(self, msg: tuple):
        """Buffer ``msg``; returns (assigned seq, depth after append)."""
        with self._lock:
            seq = self._next_seq
            self._next_seq = seq + 1
            self._entries.append((seq, msg))
            return seq, len(self._entries)

    def ack(self, seq: int) -> int:
        """Trim every entry with sequence <= ``seq`` (the head processed
        them). Returns how many entries were trimmed. Stale/duplicate
        acks (already-trimmed prefixes) are no-ops."""
        trimmed = 0
        with self._lock:
            while self._entries and self._entries[0][0] <= seq:
                self._entries.popleft()
                trimmed += 1
        return trimmed

    def pending(self):
        """Snapshot of unacked (seq, msg) entries, oldest first."""
        with self._lock:
            return list(self._entries)

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._next_seq - 1


# daemon->head tags that ride the outbox (report-class: the head must
# not lose them across a blackout); everything else is sent bare.
# "util" = resource samples for the utilization ring; "w"-wrapped
# worker "prof" batches are covered by "w" itself. The two-level
# dispatch reports (local leases, p2p completion receipts, fallbacks,
# mirrored chaos injections) are report-class BY CONSTRUCTION: the
# exactly-once story for decentralized dispatch is the outbox replay +
# head-side sequence dedup, nothing new
_OUTBOX_TAGS = frozenset((
    "w", "worker_died", "pulled", "log", "util",
    "local_lease", "local_retry", "p2p_done", "p2p_fallback", "fault"))


class _WorkerSlot:
    __slots__ = ("num", "proc", "conn", "ctrl", "pid", "returns",
                 "attempts", "gets", "actor_bin", "send_lock", "err_path",
                 "hdr_cache", "reader_done")

    def __init__(self, num: int):
        self.num = num
        # serializes writes to conn: the run loop and deferred
        # peer-pull reply threads both send here, and interleaved
        # Connection frames corrupt the worker's stream
        self.send_lock = threading.Lock()
        self.proc: Optional[subprocess.Popen] = None
        self.conn = None
        self.ctrl = None
        self.pid: Optional[int] = None
        # task_id binary -> [return oid binaries] for in-flight payloads,
        # so sealed shm returns can be rewritten on "done"
        self.returns: Dict[bytes, list] = {}
        # task_id binary -> attempt token (stamped by the head at
        # dispatch); rides the rejoin in-flight report so a restarted
        # head can discard stale-attempt replays after a resubmission
        self.attempts: Dict[bytes, int] = {}
        # req_id -> purpose ("get" | "arg") of get RPCs forwarded to
        # the head, whose replies may carry ("node_shm", oid) markers
        # to rewrite as arena locations / peer pulls (purpose sets the
        # pull priority: a blocking get outranks task-arg prefetch)
        self.gets: Dict[int, str] = {}
        # dedicated actor workers record their actor id (from the
        # actor_create payload) so a RESTARTED head can re-adopt them
        self.actor_bin: Optional[bytes] = None
        # path of this worker's .err capture file (log plane), so a
        # crash tail can ride the worker_died report to the head
        self.err_path: Optional[str] = None
        # lease-envelope header cache: the daemon decodes ("env", ...)
        # payloads for its own returns/attempts bookkeeping while
        # forwarding the blob verbatim — both caches evolve in
        # lockstep because both sides decode the same ordered stream
        self.hdr_cache: Dict[int, tuple] = {}
        # set when the worker-reader thread hits EOF with its buffered
        # messages drained; _monitor waits on it so a completion the
        # worker emitted just before dying is never retried
        self.reader_done = threading.Event()


PEER_CHUNK = 1 << 20  # ~1 MB frames (reference: ObjectBufferPool)


def _drain_frames(conn, total: int, timeout: float, sink_view=None,
                  sink_write=None) -> None:
    """The ONE chunk-protocol receive loop (exact ~1 MB frames until
    `total`): into a buffer view (recv_bytes_into, no copy) or through
    a write callback (spill files). Raises OSError on timeout/short
    frames — both fetch modes share this, so protocol changes can't
    desynchronize them."""
    pos = 0
    while pos < total:
        n = min(PEER_CHUNK, total - pos)
        if not conn.poll(timeout):
            raise OSError("peer chunk timed out")
        if sink_view is not None:
            got = conn.recv_bytes_into(sink_view[pos:pos + n])
        else:
            chunk = conn.recv_bytes(PEER_CHUNK)
            got = len(chunk)
            sink_write(chunk)
        if got != n:
            raise OSError(f"short peer chunk: {got} != {n} at {pos}")
        pos += n


def recv_object_into_store(conn, store, oid: ObjectID, total: int,
                           timeout: float) -> bool:
    """Drain the chunk frames into the given store: straight into a
    pre-created arena range (recv_bytes_into — no intermediate buffer)
    or appended to a spill file when the arena can't hold it.
    Per-transfer transient memory is ONE chunk. Shared by daemons AND
    the head (both adopt peer streams into their own ShmObjectStore)."""
    kind, target = store.begin_adopt(oid, total)
    view = target if kind == "arena" else None
    try:
        _drain_frames(conn, total, timeout, sink_view=view,
                      sink_write=None if view is not None
                      else target.write)
    except BaseException:
        if view is not None:
            view.release()
        store.abort_adopt(oid, kind,
                          None if kind == "arena" else target)
        raise
    if view is not None:
        view.release()
    store.finish_adopt(oid, total, kind,
                       None if kind == "arena" else target)
    return True


def _peer_dial(address, authkey: bytes, oid: ObjectID, timeout: float):
    """Dial a daemon's peer listener, handshake, request oid; returns
    (conn, total_bytes) or None on any failure/miss (incl. a stale
    authkey after a head restart — AuthenticationError is ProcessError,
    NOT OSError). Caller closes."""
    from multiprocessing import AuthenticationError

    from ray_tpu._private import protocol

    try:
        conn = Client(tuple(address), authkey=authkey)
    except (OSError, EOFError, ValueError, AuthenticationError):
        return None
    try:
        conn.send(protocol.make_wire_hello("peer"))
        if conn.recv() != ("ok",):
            conn.close()
            return None
        conn.send(("get", oid.binary()))
        if not conn.poll(timeout):
            conn.close()
            return None
        reply = conn.recv()
        if reply[0] == "miss":
            conn.close()
            return None
        return conn, reply[1]
    except (OSError, EOFError, ValueError, AuthenticationError):
        try:
            conn.close()
        except Exception:
            pass
        return None


def peer_pull_once(address, authkey: bytes, store, oid: ObjectID,
                   timeout: float) -> bool:
    """One-shot chunked pull of an object from a node daemon's peer
    listener into `store` (the HEAD's fetch path — daemons keep cached
    per-peer connections instead, see NodeDaemon.pull_from_peer).
    Returns True when the object is locally resident afterwards."""
    if store.contains(oid):
        return True
    dialed = _peer_dial(address, authkey, oid, timeout)
    if dialed is None:
        return False
    conn, total = dialed
    try:
        return recv_object_into_store(conn, store, oid, total, timeout)
    except (OSError, EOFError, ValueError):
        return False
    finally:
        try:
            conn.close()
        except Exception:
            pass


def peer_pull_bytes(address, authkey: bytes, oid: ObjectID,
                    timeout: float) -> Optional[bytearray]:
    """Chunked pull into ONE preallocated buffer (for heads with no
    shm arena — thread mode): the frames land via recv_bytes_into, so
    neither side ever materializes the object as a single pickled
    message and the daemon's control link stays untouched."""
    dialed = _peer_dial(address, authkey, oid, timeout)
    if dialed is None:
        return None
    conn, total = dialed
    try:
        buf = bytearray(total)
        _drain_frames(conn, total, timeout, sink_view=memoryview(buf))
        return buf
    except (OSError, EOFError, ValueError):
        return None
    finally:
        try:
            conn.close()
        except Exception:
            pass


class PullManager:
    """Priority-ordered peer pulls (reference: the object manager's
    PullManager, src/ray/object_manager/pull_manager.cc — get > wait >
    task-arg request priority, bounded concurrent transfers).

    Every peer pull enqueues here; a fixed pool of puller threads
    drains the heap strictly by (priority, arrival). A blocking user
    get therefore jumps ahead of queued task-argument prefetches, and
    per-link memory stays bounded by num_threads transfers x one
    chunk."""

    PRIO_GET, PRIO_WAIT, PRIO_ARG = 0, 1, 2

    def __init__(self, transfer, num_threads: int = 2, on_pulled=None):
        import collections

        self._transfer = transfer      # (address, oid_bin) -> bool
        # invoked with oid_bin after every SUCCESSFUL transfer (staged
        # prefetches and blocking pulls alike) — the daemon reports the
        # new local copy to the head's object directory through it
        self._on_pulled = on_pulled
        self._heap: list = []
        self._cv = threading.Condition()
        self._seq = 0
        self._stop = False
        # duplicate pulls of one object COALESCE: only the first
        # enqueues a transfer, later callers wait on its outcome — two
        # threads racing begin_adopt for the same oid would otherwise
        # corrupt a shared spill temp file or misreport "lost"
        self._inflight: Dict[bytes, list] = {}
        # bounded observability ring (a daemon lives for days)
        self.serviced: Any = collections.deque(maxlen=1024)
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"ray_tpu_pull_{i}")
            for i in range(num_threads)]
        for t in self._threads:
            t.start()

    def _enqueue_locked(self, priority: int, address, oid_bin: bytes,
                        done, slot) -> None:
        """Push a transfer onto the heap and wake a puller. Caller
        holds self._cv (the heap, _seq, and _inflight move together) —
        checked dynamically under RAY_TPU_DEBUG_LOCKS=1."""
        import heapq

        assert_holds(self._cv, "PullManager heap")
        self._inflight[oid_bin] = []
        self._seq += 1
        heapq.heappush(self._heap, (priority, self._seq,
                                    tuple(address), oid_bin, done, slot))
        self._cv.notify()

    def pull(self, address, oid_bin: bytes, priority: int) -> bool:
        """Blocking: enqueue (or join the in-flight pull of the same
        object) and wait for the outcome."""
        done = threading.Event()
        slot = [False]
        with self._cv:
            waiters = self._inflight.get(oid_bin)
            if waiters is not None:
                waiters.append((done, slot))
            else:
                self._enqueue_locked(priority, address, oid_bin, done,
                                     slot)
        done.wait()
        return slot[0]

    def prefetch(self, address, oid_bin: bytes, priority: int) -> None:
        """Fire-and-forget: enqueue a pull without waiting for it
        (dispatch-time arg staging). A pull of the same object already
        in flight coalesces to a no-op; a later blocking pull() of the
        object joins this transfer's waiters as usual."""
        with self._cv:
            if oid_bin in self._inflight:
                return
            self._enqueue_locked(priority, address, oid_bin,
                                 threading.Event(), [False])

    def _run(self) -> None:
        import heapq

        while True:
            with self._cv:
                while not self._heap and not self._stop:
                    self._cv.wait()
                if self._stop:
                    return
                prio, _seq, address, oid_bin, done, slot = heapq.heappop(
                    self._heap)
                self.serviced.append((prio, oid_bin))
            try:
                ok = bool(self._transfer(address, oid_bin))
            except BaseException:
                ok = False
            with self._cv:
                waiters = self._inflight.pop(oid_bin, [])
            slot[0] = ok
            done.set()
            for d, s in waiters:
                s[0] = ok
                d.set()
            if ok and self._on_pulled is not None:
                try:
                    self._on_pulled(oid_bin)
                except Exception:
                    pass  # reporting must never kill a puller thread

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()


class NodeDaemon:
    def __init__(self, head_address, head_authkey: bytes,
                 node_token: str, object_store_memory: int,
                 inline_max: int, spill_dir: Optional[str] = None,
                 join_info: Optional[dict] = None,
                 rejoin_timeout_s: float = 20.0):
        from ray_tpu._private.runtime.shm_store import ShmObjectStore

        self.store = ShmObjectStore(object_store_memory,
                                    spill_dir=spill_dir)
        self.inline_max = inline_max
        self._slots: Dict[int, _WorkerSlot] = {}
        self._lock = threading.Lock()
        self._shutdown = False
        self._head_address = tuple(head_address)
        self._head_authkey = head_authkey
        self._node_info = dict(join_info or {})
        # control-plane FT: a lost head connection WITHOUT an explicit
        # exit leaves this node orphaned-but-alive; it re-dials the head
        # address (same cluster secret, persisted beside the head's GCS
        # journal) for this long before giving up. Workers — and actor
        # STATE living in their processes — survive the head restart.
        self._rejoin_timeout_s = rejoin_timeout_s

        # log plane: this node's capture directory. The head points it
        # somewhere meaningful via RAY_TPU_LOG_DIR when it spawns us
        # (same-host clusters nest it under the head's session dir);
        # self-started daemons get their own session dir. Workers'
        # stdout/stderr land here, a tailer ships appended lines to
        # the head, and log_list/log_read queries read from here.
        from ray_tpu._private import log_plane

        env_dir = os.environ.get("RAY_TPU_LOG_DIR", "")
        self.log_dir = log_plane.resolve_session_log_dir(env_dir)
        try:
            self._log_rotate = int(os.environ.get(
                log_plane.ENV_LOG_ROTATE_BYTES, "0") or 0)
            self._log_backups = int(os.environ.get(
                log_plane.ENV_LOG_ROTATE_BACKUPS, "0") or 0)
        except ValueError:
            self._log_rotate, self._log_backups = 0, 0
        if not self._log_rotate:
            from ray_tpu._private.config import GLOBAL_CONFIG
            self._log_rotate = GLOBAL_CONFIG.log_rotation_bytes
            self._log_backups = GLOBAL_CONFIG.log_rotation_backups
        self._log_offsets: Dict[str, int] = {}

        # workers dial this daemon, never the head (they may share no
        # filesystem/host with it)
        self._authkey = os.urandom(16)
        self._sock_dir = tempfile.mkdtemp(prefix="ray_tpu_node_")
        self._listener = Listener(
            address=os.path.join(self._sock_dir, "node.sock"),
            family="AF_UNIX", authkey=self._authkey)

        # peer transfer plane (reference: the object manager's
        # node-to-node Pull/Push protocol, ray: src/ray/object_manager/
        # — bytes move DIRECTLY between the producing and consuming
        # nodes; the head only answers "who has it"). The cluster
        # secret (head authkey) guards peer connections too.
        import socket

        self._peer_authkey = head_authkey
        self._peer_listener = Listener(("0.0.0.0", 0),
                                       authkey=head_authkey)
        # advertise the address peers can reach: the local IP of our
        # route to the head (localhost clusters advertise 127.0.0.1).
        # UDP connect: routes without sending a packet — a TCP probe
        # would hit the head's authenticated listener and poison its
        # accept loop with a failed HMAC challenge
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect(tuple(head_address))
            local_ip = probe.getsockname()[0]
        except OSError:
            local_ip = "127.0.0.1"
        finally:
            probe.close()
        self.peer_address = (local_ip, self._peer_listener.address[1])
        self._peer_conns: Dict[tuple, Any] = {}
        self._peer_lock = threading.Lock()
        self.pulls = PullManager(self.pull_from_peer,
                                 on_pulled=self._report_pulled)
        threading.Thread(target=self._peer_accept_loop, daemon=True,
                         name="ray_tpu_node_peer_accept").start()

        # two-level dispatch state (bottom-up scheduler + p2p actors).
        # Everything defaults OFF: until the head pushes a resview the
        # daemon is a pure forwarder, byte-for-byte pre-two-level.
        self._resview: Dict[str, Any] = {}
        self._resview_lock = threading.Lock()
        self._resview_v = 0                # adopted view version
        self._resident_digest: frozenset = frozenset()
        self._chaos_snapshot: Optional[dict] = None
        self._local_tids: set = set()      # locally-admitted, in flight
        self._local_dispatched = 0
        # locally-admitted lease bodies retained for LOCAL retries:
        # tid -> {payload, info, attempt, max_retries, arg_refs}. A
        # worker death re-leases an unfinished entry to a sibling
        # worker (attempt+1) up to max_retries; the entry dies with
        # the task's done/err
        self._local_leases: Dict[bytes, dict] = {}
        # p2p actor plane: head-resolved routes, per-actor task-id
        # minting salts, A-side in-flight calls, per-peer actor lanes,
        # and B-side pending executions awaiting their result send
        self._actor_routes: Dict[bytes, tuple] = {}
        self._aresolve_last: Dict[bytes, float] = {}
        # peer addresses the head declared DEAD (("node_dead", info)
        # broadcast): their gossiped views are ghosts — never adopt
        # one, never gossip to them. Entries clear when a head-pushed
        # view re-lists the address (the node rejoined).
        self._dead_peers: set = set()
        self._actor_salts: Dict[bytes, list] = {}
        self._p2p_calls: Dict[bytes, dict] = {}
        self._p2p_lanes: Dict[tuple, dict] = {}
        self._p2p_pending: Dict[bytes, tuple] = {}
        self._p2p_lock = threading.Lock()

        # report-class messages are sequenced through the outbox so a
        # head blackout loses nothing (see module docstring)
        self._outbox = _Outbox()
        self._head = Client(head_address, authkey=head_authkey)
        self._head_lock = threading.Lock()
        # arena name travels in the hello so the head can reap the
        # segment if this daemon is SIGKILLed (machine-death chaos).
        # token "join" = self-started daemon (ray_tpu start --address):
        # declared resources travel too and the head ADOPTS the node.
        # The peer transfer address rides at the tuple tail.
        from ray_tpu._private.protocol import make_wire_hello

        if node_token == "join":
            self._head.send(make_wire_hello(
                "join", os.getpid(), self.store.arena.name,
                dict(join_info or {}), tuple(self.peer_address)))
        else:
            self._head.send(make_wire_hello(
                node_token, os.getpid(), self.store.arena.name,
                tuple(self.peer_address)))
        # clock handshake: one wall/perf sample right after the hello;
        # the head derives clock_offset = head_wall - daemon_wall so
        # worker-side execution windows land on the head's time axis
        self._head.send(("clock", time.time(), time.perf_counter()))

    # ------------------------------------------------------------------
    def _report_pulled(self, oid_bin: bytes) -> None:
        """A peer pull landed locally: tell the head so the object
        directory gains this node as a SECONDARY location (runs on
        puller threads; _send_head serializes under _head_lock)."""
        self._send_head(("pulled", oid_bin))

    def _send_head(self, msg: tuple) -> None:
        if msg[0] in _OUTBOX_TAGS:
            # report-class: buffer first, THEN try to send — a failed
            # send just leaves the entry in the outbox for the rejoin
            # replay (exactly-once: the head dedups by sequence)
            seq, depth = self._outbox.append(msg)
            self._send_head_raw(("seq", seq, depth, False, msg))
        else:
            self._send_head_raw(msg)

    def _send_head_raw(self, msg: tuple) -> None:
        try:
            with self._head_lock:
                self._head.send(msg)
        except (OSError, ValueError):
            # head gone: outbox entries replay on rejoin; bare
            # request/reply traffic is moot (its requester died with
            # the link) and the main loop handles reconnecting
            pass

    def _replay_outbox(self) -> None:
        """Re-send every unacked report to the (re)joined head, flagged
        as replay. The head processes entries it has never seen and
        drops duplicates by sequence number — a flap mid-replay just
        triggers another (still deduped) replay on the next rejoin."""
        pending = self._outbox.pending()
        for i, (seq, msg) in enumerate(pending):
            self._send_head_raw(("seq", seq, len(pending) - i, True, msg))

    # ------------------------------------------------------------------
    # worker lifecycle
    # ------------------------------------------------------------------
    def _spawn(self, num: int, wid_hex: Optional[str] = None) -> None:
        slot = _WorkerSlot(num)
        with self._lock:
            self._slots[num] = slot
        # by default workers don't own an accelerator (the head holds
        # the single-chip lease) — strip the plugin vars so a degraded
        # tunnel can't hang their `import jax`; worker_tpu_access
        # opts a node's workers back in (same knob process_pool honors)
        from ray_tpu._private import log_plane, spawn_env
        from ray_tpu._private.config import GLOBAL_CONFIG
        extra = {"RAY_TPU_AUTHKEY": self._authkey.hex()}
        if GLOBAL_CONFIG.profile_hz > 0:
            # propagate the head's profile knob (this daemon got it the
            # same way, via its own spawn env) so remote workers sample
            extra["RAY_TPU_PROFILE_HZ"] = str(GLOBAL_CONFIG.profile_hz)
        stem = (f"worker-{wid_hex}" if wid_hex
                else f"worker-{num}-{os.getpid()}")
        log_env = log_plane.child_log_env(
            self.log_dir, stem, self._log_rotate, self._log_backups)
        slot.err_path = log_env.get(log_plane.ENV_LOG_ERR)
        extra.update(log_env)
        env = spawn_env.child_env(
            use_accelerator=GLOBAL_CONFIG.worker_tpu_access,
            inherit_sys_path=True,
            extra=extra)
        slot.proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.runtime.worker_process",
             self._listener.address, self.store.arena.name,
             str(self.inline_max), str(num)],
            env=env, close_fds=True)
        slot.pid = slot.proc.pid
        threading.Thread(target=self._monitor, args=(slot,), daemon=True,
                         name=f"ray_tpu_node_monitor_{num}").start()

    def _monitor(self, slot: _WorkerSlot) -> None:
        slot.proc.wait()
        if slot.conn is not None:
            # completions the worker emitted just before dying may
            # still sit buffered on its pipe: wait for the reader to
            # drain to EOF so a finished task is never retried
            slot.reader_done.wait(1.0)
        with self._lock:
            gone = self._slots.pop(slot.num, None)
        if gone is not None and not self._shutdown:
            from ray_tpu._private import log_plane

            # local retries FIRST: the outbox FIFO lands each
            # ("local_retry", ...) before the worker_died report, so
            # the head re-homes those adopted leases instead of
            # failing them with the rest of the dead worker's inflight
            self._retry_local_leases(slot)
            tail = log_plane.err_tail_message(slot.err_path)
            self._send_head(("worker_died", slot.num,
                             slot.proc.returncode, tail))

    def _retry_local_leases(self, slot: _WorkerSlot) -> None:
        """Per-attempt accounting for locally-dispatched leases
        (tentpole: retry-carrying tasks dispatch locally): every
        unfinished local lease on a dead worker re-leases to a sibling
        worker with attempt+1, as long as admission still holds
        (attempts left, arg bytes still resident, a live slot exists).
        Anything else falls through to the head's worker_died handling
        — the head owns terminal failure and lineage reconstruction."""
        with self._resview_lock:
            accept = bool(self._resview.get("accept"))
        for tid_bin in list(slot.returns):
            lease = self._local_leases.get(tid_bin)
            if lease is None:
                continue  # head-placed: the head's retry policy runs
            slot.returns.pop(tid_bin, None)
            slot.attempts.pop(tid_bin, None)
            attempt = int(lease.get("attempt", 0)) + 1
            target = None
            if (accept and attempt <= int(lease.get("max_retries", 0))
                    and self._refs_resident(lease.get("arg_refs"))):
                target = self._pick_local_slot(slot)
            if target is None:
                # exhausted / args gone / no slot: release the lease;
                # the worker_died report reaches the head with this
                # tid still adopted and the head fails or rebuilds it
                self._local_leases.pop(tid_bin, None)
                with self._resview_lock:
                    self._local_tids.discard(tid_bin)
                continue
            lease["attempt"] = attempt
            payload = dict(lease["payload"], attempt=attempt)
            info = dict(lease["info"], worker_num=target.num,
                        attempt=attempt, t=time.time())
            lease["info"] = info
            target.returns[tid_bin] = list(payload["return_ids"])
            target.attempts[tid_bin] = attempt
            self._send_head(("local_retry", tid_bin, info))
            self._to_worker(target, ("task", payload))

    def _accept_loop(self) -> None:
        from multiprocessing import AuthenticationError

        while not self._shutdown:
            try:
                conn = self._listener.accept()
            except AuthenticationError:
                continue  # a stale/foreign dialer must not kill accepts
            except (OSError, EOFError):
                return
            try:
                hello = conn.recv()
            except (EOFError, OSError):
                conn.close()
                continue
            from ray_tpu._private import protocol

            ver, fields = protocol.split_any_hello(hello)
            if len(fields) != 2:
                conn.close()
                continue
            if ver != protocol.PROTOCOL_VERSION:
                try:
                    conn.send(protocol.mismatch_error("node daemon", ver))
                except (OSError, ValueError):
                    pass
                conn.close()
                continue
            num, kind = fields
            with self._lock:
                slot = self._slots.get(num)
            if slot is None:
                conn.close()
                continue
            if kind == "task":
                slot.conn = conn
                threading.Thread(target=self._worker_reader,
                                 args=(slot,), daemon=True,
                                 name=f"ray_tpu_node_reader_{num}").start()
            else:
                slot.ctrl = conn

    # ------------------------------------------------------------------
    # worker -> head forwarding, with node-local interception
    # ------------------------------------------------------------------
    def _worker_reader(self, slot: _WorkerSlot) -> None:
        try:
            while True:
                try:
                    msg = slot.conn.recv()
                except (EOFError, OSError):
                    return  # _monitor reports the death
                out = self._intercept(slot, msg)
                if out is not None:
                    self._send_head(("w", slot.num, out))
        finally:
            slot.reader_done.set()  # buffered completions all drained

    def _intercept(self, slot: _WorkerSlot, msg: tuple) -> Optional[tuple]:
        """Serve node-local object-plane ops; rewrite sealed returns.
        Returns the message to forward to the head, or None if fully
        handled here."""
        kind = msg[0]
        if kind == "rpc":
            _, req_id, op, args = msg
            if op == "create":
                oid_bin, nbytes = args
                try:
                    offset = self.store.create(ObjectID(oid_bin), nbytes)
                    reply = ("reply", req_id, True, offset)
                except BaseException as e:  # noqa: BLE001
                    import cloudpickle
                    reply = ("reply", req_id, False, cloudpickle.dumps(e))
                self._to_worker(slot, reply)
                return None
            if op == "put":
                oid_bin, loc = args
                if loc[0] == "shm":
                    # seal here; the head records the location only
                    self.store.seal(ObjectID(oid_bin))
                    return ("rpc", req_id, "put",
                            (oid_bin, ("remote_shm", loc[2])))
                return msg
            if op == "get":
                oid_bins, timeout = args[0], args[1]
                purpose = args[2] if len(args) > 2 else "get"
                locs = []
                for b in oid_bins:
                    loc = self.store.locate(ObjectID(b))
                    if loc is None:
                        # something not arena-resident (unsealed, spilled,
                        # exception, or remote): the head decides; its
                        # reply may point back here via node_shm markers
                        slot.gets[req_id] = purpose
                        return ("rpc", req_id, "get",
                                (oid_bins, timeout))
                    locs.append(("shm", loc[0], loc[1]))
                self._to_worker(slot, ("reply", req_id, True, locs))
                return None
            if op == "submit":
                return self._maybe_local_submit(slot, req_id, args)
            if op == "actor_call":
                return self._maybe_p2p_call(slot, req_id, args)
            return msg
        if kind == "ready":
            # late-attaching worker: advertise the currently-enabled
            # two-level lanes (workers alive at resview time get the
            # advert through _apply_resview's broadcast instead)
            with self._resview_lock:
                accept = bool(self._resview.get("accept"))
                p2p = bool(self._resview.get("p2p"))
            if accept or p2p:
                self._to_worker(slot, ("p2p", accept, p2p))
            return msg
        if kind in ("done",):
            task_id_bin, entries = msg[1], msg[2]
            with self._p2p_lock:
                p2p = self._p2p_pending.pop(task_id_bin, None)
            if p2p is not None:
                self._finish_p2p_exec(slot, task_id_bin, p2p, msg)
                return None
            return_bins = slot.returns.pop(task_id_bin, [])
            slot.attempts.pop(task_id_bin, None)
            out = []
            for i, entry in enumerate(entries):
                if entry[0] == "shm" and i < len(return_bins):
                    rid = ObjectID(return_bins[i])
                    if self.store.locate(rid) is None:
                        # a dedup re-emission (p2p attempt already ran
                        # here) replays already-sealed entries; sealing
                        # twice would corrupt the arena accounting
                        self.store.seal(rid)
                    out.append(("remote_shm", entry[2]))
                else:
                    out.append(entry)
            with self._resview_lock:
                self._local_tids.discard(task_id_bin)
            self._local_leases.pop(task_id_bin, None)
            # preserve any trailing fields (e.g. the execution-window
            # timing tuple the task event plane rides on)
            return (msg[0], task_id_bin, out) + tuple(msg[3:])
        if kind == "err":
            with self._p2p_lock:
                p2p = self._p2p_pending.pop(msg[1], None)
            if p2p is not None:
                self._finish_p2p_exec(slot, msg[1], p2p, msg)
                return None
            slot.returns.pop(msg[1], None)
            slot.attempts.pop(msg[1], None)
            with self._resview_lock:
                self._local_tids.discard(msg[1])
            self._local_leases.pop(msg[1], None)
        return msg

    def _serve_fetch(self, fid: int, oid_bin: bytes) -> None:
        sobj = self.store.get_serialized(ObjectID(oid_bin))
        if sobj is None:
            self._send_head(("fetched", fid, False, None))
        else:
            self._send_head(("fetched", fid, True, sobj.to_bytes()))

    # ------------------------------------------------------------------
    # log plane: queries + tailer (ship appended lines to the head)
    # ------------------------------------------------------------------
    def _serve_log_list(self, rid: int) -> None:
        from ray_tpu._private import log_plane

        self._send_head(("log_listed", rid,
                         log_plane.list_log_files(self.log_dir)))

    def _serve_log_read(self, rid: int, filename: str,
                        tail: Optional[int]) -> None:
        from ray_tpu._private import log_plane

        try:
            text = log_plane.read_log(self.log_dir, filename, tail)
            self._send_head(("log_data", rid, True, text))
        except (OSError, ValueError) as e:
            self._send_head(("log_data", rid, False, str(e)))

    def _log_tail_loop(self) -> None:
        """Ship appended capture-file lines to the head every ~0.3s.

        Reads bytes past the last shipped offset per file, splits
        complete lines and batches them as ("log", fname, lines). The
        head's LogMonitor attributes and re-emits them; when log
        streaming is off the head just drops them. Partial trailing
        lines stay unshipped until their newline arrives (and a
        bounded per-tick read keeps one spamming worker from wedging
        the daemon's send lock)."""
        import time as _time

        while not self._shutdown:
            _time.sleep(0.3)
            try:
                names = sorted(os.listdir(self.log_dir))
            except OSError:
                continue
            for n in names:
                if not (n.endswith(".out") or n.endswith(".err")):
                    continue
                path = os.path.join(self.log_dir, n)
                try:
                    size = os.path.getsize(path)
                except OSError:
                    continue
                pos = self._log_offsets.get(n, 0)
                if size < pos:  # rotated underneath us
                    pos = 0
                if size == pos:
                    continue
                try:
                    with open(path, "rb") as f:
                        f.seek(pos)
                        data = f.read(1 << 20)
                except OSError:
                    continue
                last_nl = data.rfind(b"\n")
                if last_nl < 0:
                    self._log_offsets[n] = pos
                    continue
                self._log_offsets[n] = pos + last_nl + 1
                lines = data[:last_nl].decode(
                    "utf-8", "replace").split("\n")
                if lines:
                    self._send_head(("log", n, lines))

    # ------------------------------------------------------------------
    # utilization sampling (profile plane, profile_hz > 0 only)
    # ------------------------------------------------------------------
    def _ship_util(self, payload: dict) -> None:
        """One resource sample for the head's utilization ring. "util"
        is report-class (rides the outbox), so samples taken during a
        head blackout land, deduped and in order, after rejoin."""
        self._send_head(("util", payload))

    def _start_util_sampler(self) -> None:
        from ray_tpu._private.config import GLOBAL_CONFIG

        if GLOBAL_CONFIG.profile_hz <= 0 \
                or GLOBAL_CONFIG.utilization_interval_s <= 0:
            return
        from ray_tpu._private import profile_plane

        store = self.store

        def _arena_used() -> int:
            return max(store.arena.size - store.arena.free_bytes(), 0)

        self._util_sampler = profile_plane.ResourceSampler(
            GLOBAL_CONFIG.utilization_interval_s, self._ship_util,
            gauges={"arena_used_bytes": _arena_used},
            name="ray_tpu_node_util").start()

    # ------------------------------------------------------------------
    # peer transfer plane (direct node-to-node pulls)
    # ------------------------------------------------------------------
    def _peer_accept_loop(self) -> None:
        from multiprocessing import AuthenticationError

        while not self._shutdown:
            try:
                conn = self._peer_listener.accept()
            except AuthenticationError:
                continue  # bad-key dial must not kill the peer plane
            except (OSError, EOFError):
                return
            threading.Thread(target=self._peer_serve, args=(conn,),
                             daemon=True,
                             name="ray_tpu_node_peer_serve").start()

    def _peer_serve(self, conn) -> None:  # noqa: D401
        """One persistent connection per consuming peer: a versioned
        hello first, then get requests served out of the local
        arena/spill tier in ~1 MB frames — a multi-GB object never
        materializes as one message on either side (reference:
        src/ray/object_manager/ chunked push via ObjectBufferPool)."""
        from ray_tpu._private import protocol

        try:
            try:
                hello = conn.recv()
            except (EOFError, OSError):
                return
            # the peer plane speaks the proto3 envelope (wire.proto);
            # legacy tuple hellos still parse so skew fails cleanly
            ver, _fields = protocol.split_any_hello(hello)
            if ver != protocol.PROTOCOL_VERSION:
                try:
                    # schema'd rejection: the Reject envelope is what a
                    # cross-language dialer can actually parse
                    conn.send(protocol.proto_reject(
                        protocol.mismatch_error("peer plane", ver)[1]))
                except (OSError, ValueError):
                    pass
                return
            try:
                conn.send(("ok",))
            except (OSError, ValueError):
                return
            # one send lock per serving connection: chunked object
            # streams (this thread) and async ("ares", ...) result
            # frames (worker-reader threads, p2p exec) share the pipe,
            # and interleaved frames would desynchronize the protocol
            send_lock = threading.Lock()
            hdr_cache: Dict[int, tuple] = {}
            while not self._shutdown:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                if not (isinstance(msg, tuple) and msg):
                    return
                if msg[0] == "get":
                    with send_lock:
                        ok = self._peer_send_object(conn, ObjectID(msg[1]))
                    if not ok:
                        return
                elif msg[0] == "acall":
                    # p2p actor-call frame: lease-envelope encoded
                    # payloads dispatched straight to the resident
                    # actor worker; results return on THIS connection
                    self._serve_acall(conn, send_lock, hdr_cache, msg[1])
                elif msg[0] == "rview":
                    # peer-gossiped resource view: adopt if strictly
                    # fresher (same head epoch) so local admission
                    # stays current through a slow/rejoining head
                    self._apply_resview(msg[1], from_peer=True)
                else:
                    return
        finally:
            try:
                conn.close()
            except Exception:
                pass

    def _peer_send_object(self, conn, oid: ObjectID) -> bool:
        """("meta", total) + raw ~1 MB frames; arena objects stream
        zero-copy from the pinned range, spilled objects stream from
        their file. Returns False on a dead connection."""
        CH = PEER_CHUNK
        view = self.store.acquire_raw(oid)
        if view is not None:
            try:
                total = len(view)
                conn.send(("meta", total))
                for off in range(0, total, CH):
                    conn.send_bytes(view[off:off + CH])
                return True
            except (OSError, ValueError):
                return False
            finally:
                view.release()
                self.store.release_raw(oid)
        spilled = self.store.spilled_path(oid)
        if spilled is not None:
            path, total = spilled
            try:
                f = open(path, "rb")
            except OSError as e:
                # nothing streamed yet: a miss reply keeps the
                # connection usable
                try:
                    conn.send(("miss", str(e)))
                    return True
                except (OSError, ValueError):
                    return False
            try:
                conn.send(("meta", total))
                while True:
                    chunk = f.read(CH)
                    if not chunk:
                        break
                    conn.send_bytes(chunk)
                return True
            except OSError:
                # MID-STREAM failure: the chunk protocol is now
                # desynchronized — kill the connection deliberately
                # (injecting a control frame would reach the receiver
                # as a corrupt chunk); the puller redials fresh
                return False
            finally:
                f.close()
        try:
            conn.send(("miss", None))
            return True
        except (OSError, ValueError):
            return False

    def pull_from_peer(self, address: tuple,
                       oid_bin: bytes) -> bool:
        """Pull an object from the producing node's daemon into THIS
        node's store, ~1 MB frames at a time: arena-resident when it
        fits, streamed straight to the spill tier when it doesn't — a
        >arena-sized object transfers without either side holding it
        whole (reference: PullManager + ObjectBufferPool chunking).
        Returns True when the object is locally resident afterwards.

        Connections cache per peer with a per-peer lock
        (a stalled peer must not wedge pulls from OTHER peers), replies
        are awaited under the transfer timeout, and a dead cached
        connection gets ONE fresh redial — after that the producer is
        treated as unreachable (the head-relay path would be talking to
        the same dead daemon)."""
        from ray_tpu._private.config import GLOBAL_CONFIG

        address = tuple(address)
        timeout = GLOBAL_CONFIG.object_transfer_timeout_s
        with self._peer_lock:
            entry = self._peer_conns.get(address)
            if entry is None:
                entry = [None, threading.Lock()]
                self._peer_conns[address] = entry
        from ray_tpu._private import protocol

        oid = ObjectID(oid_bin)
        if self.store.contains(oid):
            return True  # a concurrent pull already landed it
        for _attempt in (0, 1):
            with entry[1]:
                try:
                    if entry[0] is None:
                        c = Client(address, authkey=self._peer_authkey)
                        c.send(protocol.make_wire_hello("peer"))
                        ack = c.recv()
                        if ack != ("ok",):
                            # version rejection: log the peer's reason
                            import logging
                            logging.getLogger(__name__).error(
                                "peer %s rejected us: %s", address, ack)
                            c.close()
                            return False
                    entry[0] = c if entry[0] is None else entry[0]
                    conn = entry[0]
                    conn.send(("get", oid_bin))
                    if not conn.poll(timeout):
                        raise OSError("peer reply timed out")
                    reply = conn.recv()
                    if reply[0] == "miss":
                        return False
                    total = reply[1]
                    return self._recv_object(conn, oid, total, timeout)
                except (OSError, EOFError, ValueError):
                    # drop the (possibly dead) connection; the second
                    # attempt dials fresh
                    try:
                        if entry[0] is not None:
                            entry[0].close()
                    except Exception:
                        pass
                    entry[0] = None
        return False

    def _recv_object(self, conn, oid: ObjectID, total: int,
                     timeout: float) -> bool:
        return recv_object_into_store(conn, self.store, oid, total,
                                      timeout)

    def _localize(self, loc: tuple, priority: int = 0) -> tuple:
        """Rewrite a head get-reply entry: ("node_shm", oid) points at
        THIS node's store (zero-copy arena location / spill restore);
        ("peer", oid, address) directs a DIRECT pull from the producing
        node's daemon — the bytes never touch the head. Peer pulls go
        through the priority pull manager (get > wait > task-arg) and
        land in the LOCAL store, so the worker reads the result
        zero-copy from the arena (or from the spill file for objects
        bigger than the arena)."""
        if not (isinstance(loc, tuple) and loc):
            return loc
        if loc[0] == "peer":
            oid = ObjectID(loc[1])
            if self.pulls.pull(loc[2], loc[1], priority):
                return self._local_loc(oid)
            return self._lost(oid)
        if loc[0] != "node_shm":
            return loc
        return self._local_loc(ObjectID(loc[1]))

    def _local_loc(self, oid: ObjectID) -> tuple:
        """A worker-readable location for a locally-resident object."""
        arena_loc = self.store.locate(oid)
        if arena_loc is not None:
            return ("shm", arena_loc[0], arena_loc[1])
        spilled = self.store.spilled_path(oid)
        if spilled is not None:
            # same host: the worker reads the spill file itself — a
            # >arena-sized object never rides the pipe as one message
            return ("spill_file", spilled[0], spilled[1])
        sobj = self.store.get_serialized(oid)
        if sobj is not None:
            return ("inline", sobj.to_bytes())
        return self._lost(oid)

    def _localize_reply(self, slot, req_id, locs, priority: int) -> None:
        self._to_worker(slot, ("reply", req_id, True,
                               [self._localize(lc, priority)
                                for lc in locs]))

    def _lost(self, oid: ObjectID) -> tuple:
        import cloudpickle

        from ray_tpu import exceptions as rex
        return ("exc", cloudpickle.dumps(
            rex.ObjectLostError(oid.hex())))

    def _to_worker(self, slot: _WorkerSlot, msg: tuple) -> None:
        try:
            with slot.send_lock:
                slot.conn.send(msg)
        except (OSError, ValueError):
            pass

    # ------------------------------------------------------------------
    # two-level dispatch: node-local submission queue (tentpole a)
    # ------------------------------------------------------------------
    def _apply_resview(self, view: dict, from_peer: bool = False) -> None:
        """Head-pushed (or peer-gossiped) resource view: gates local
        admission (accept/cap), records the residency digest for
        ref-arg admission, advertises the p2p actor lane to this
        node's workers, and mirrors the head's armed chaos plan so
        daemon-hosted sites (peer_link) fire at their seeded arrivals
        on the process that actually owns them.

        Gossiped views adopt only on a STRICTLY newer version — the
        head's direct push stays the authoritative tiebreaker — and
        keep this node's own node-scoped fields (node index, residency
        digest): a peer's digest describes the peer's arena."""
        if from_peer:
            # ghost-view eviction: the head declared the gossiping
            # node dead — a view it shipped pre-death (arriving late
            # over a still-draining lane) must never gate admission
            origin = view.get("from")
            with self._p2p_lock:
                if origin is not None \
                        and tuple(origin) in self._dead_peers:
                    return
        else:
            # a head-pushed peers list re-listing an address clears
            # its death mark (the node rejoined under a fresh daemon)
            listed = {tuple(p) for p in view.get("peers") or ()}
            if listed:
                with self._p2p_lock:
                    self._dead_peers -= listed
        with self._resview_lock:
            if from_peer:
                # same head instance (epoch) and strictly newer only:
                # a restarted head's fresh v=1 push must never lose to
                # a peer still gossiping the dead head's high-v view
                if (view.get("e") != self._resview.get("e")
                        or int(view.get("v") or 0) <= self._resview_v):
                    return
                view = dict(view,
                            node=self._resview.get("node"),
                            resident=self._resview.get("resident"))
            prev = (bool(self._resview.get("accept")),
                    bool(self._resview.get("p2p")))
            self._resview = dict(view)
            self._resview_v = int(view.get("v") or 0)
            digest = view.get("resident")
            self._resident_digest = (frozenset(digest) if digest
                                     else frozenset())
            snap = view.get("chaos")
            chaos_changed = snap != self._chaos_snapshot
            if chaos_changed:
                self._chaos_snapshot = snap
        if chaos_changed:
            from ray_tpu._private.chaos import get_controller
            try:
                get_controller().arm_snapshot(snap)
            except Exception:
                pass
        cur = (bool(view.get("accept")), bool(view.get("p2p")))
        if cur != prev:
            with self._lock:
                slots = [s for s in self._slots.values()
                         if s.conn is not None]
            for s in slots:
                self._to_worker(s, ("p2p", cur[0], cur[1]))

    def _pick_local_slot(self, submitter: _WorkerSlot):
        """Least-loaded live non-actor worker; the submitter itself
        only as a last resort (it is busy running the submitting task,
        though its nested-execution loop would still make progress)."""
        with self._lock:
            cands = [s for s in self._slots.values()
                     if s.conn is not None and s.actor_bin is None
                     and s.proc is not None and s.proc.poll() is None]
        if not cands:
            return None
        cands.sort(key=lambda s: (s.num == submitter.num,
                                  len(s.returns)))
        return cands[0]

    def _refs_resident(self, refs) -> bool:
        """Every arg ObjectRef's bytes provably on this node: sealed
        in the local arena, or listed in the head-pushed object-
        directory residency digest (8-byte oid prefixes; a prefix
        false-positive just costs one head-served get at exec time)."""
        if not refs:
            return True
        with self._resview_lock:
            digest = self._resident_digest
        for b in refs:
            if self.store.contains(ObjectID(b)):
                continue
            if digest and bytes(b)[:8] in digest:
                continue
            return False
        return True

    def _maybe_local_submit(self, slot: _WorkerSlot, req_id: int,
                            args: tuple) -> Optional[tuple]:
        """LocalScheduler admission: a worker-originated nested
        submission whose demand fits this node is leased HERE — ids
        minted locally, the lease journaled at the head through the
        report-class outbox (so head-restart reconciliation and
        exactly-once dedup come for free), the payload dispatched to a
        sibling worker without any head round-trip. Retry-carrying
        tasks admit (the daemon re-leases failed attempts locally, see
        _retry_local_leases) and ref-carrying args admit when the
        bytes are provably on-node. Everything else spills upward,
        flagged with the REASON so the head counts per-reason
        spillback: the head scheduler stays the single placement
        authority for cross-node balancing, placement groups and
        non-resident deps."""
        import cloudpickle

        fwd = ("rpc", req_id, "submit", args)
        with self._resview_lock:
            view = self._resview
            accept = bool(view.get("accept"))
            cap = int(view.get("cap") or 0)
            job_bin = view.get("job")
            watermark = view.get("wm")
            depth = len(self._local_tids)
        if not accept or job_bin is None:
            return fwd

        def spill(reason: str) -> tuple:
            return ("rpc", req_id, "submit", (args[0], reason))

        if depth >= cap:
            # bounded local queue: overflow goes upward
            return spill("queue_full")
        try:
            d = cloudpickle.loads(args[0])
        except Exception:
            return fwd
        if watermark is not None \
                and int(d.get("priority") or 0) < int(watermark):
            # QoS top-spilled-tier watermark (config.qos): work at a
            # higher tier is still queued at the head, so locally
            # admitting this lower-tier task would let it jump the
            # line — spill upward and let the head's fair-share order
            # decide (the plane off pushes no "wm" key at all)
            return spill("tier")
        res = d.get("resources") or {}
        if d.get("pg_id") is not None:      # placement is the head's
            return spill("pg")
        if res and res != {"CPU": 1} and res != {"CPU": 1.0}:
            return spill("resources")
        arg_refs = list(d.get("arg_refs") or ())
        if d.get("has_refs") is not False:
            # ref-carrying args admit only when every dep's bytes are
            # provably resident (a pre-digest submitter advertises
            # has_refs without the ref list: spill, owner resolves)
            if not arg_refs or not self._refs_resident(arg_refs):
                return spill("refs")
        target = self._pick_local_slot(slot)
        if target is None:
            return spill("no_slot")
        from ray_tpu._private.runtime.worker_process import fn_id_of

        tid = TaskID.of(JobID(job_bin))
        tid_bin = tid.binary()
        rids = [ObjectID.for_task_return(tid, i).binary()
                for i in range(d["num_returns"])]
        fn_blob = d["func_blob"]
        max_retries = int(d.get("max_retries") or 0)
        payload = {
            "task_id": tid_bin, "name": d.get("name"),
            "fn_id": fn_id_of(fn_blob), "fn_blob": fn_blob,
            "args_blob": d["args_blob"],
            "num_returns": d["num_returns"],
            "return_ids": rids, "attempt": 0,
        }
        parent = d.get("trace")
        if parent is not None and parent[3]:
            payload["trace"] = (parent[0], os.urandom(8).hex(),
                                parent[1], True)
        info = {
            "name": d.get("name"), "fn_blob": fn_blob,
            "args_blob": d["args_blob"],
            "num_returns": d["num_returns"], "returns": rids,
            "resources": dict(res), "worker_num": target.num,
            "submitter": slot.num, "trace": payload.get("trace"),
            "attempt": 0, "max_retries": max_retries,
            "arg_refs": arg_refs, "t": time.time(),
        }
        with self._resview_lock:
            self._local_tids.add(tid_bin)
            self._local_dispatched += 1
        if max_retries > 0:
            # retain the lease body so a worker death can re-lease the
            # attempt locally instead of consulting the head
            self._local_leases[tid_bin] = {
                "payload": payload, "info": info, "attempt": 0,
                "max_retries": max_retries, "arg_refs": arg_refs,
            }
        target.returns[tid_bin] = list(rids)
        target.attempts[tid_bin] = 0
        # lease report FIRST: outbox FIFO means the head always sees
        # the lease before the completion the target worker produces
        self._send_head(("local_lease", tid_bin, info))
        self._to_worker(target, ("task", payload))
        self._to_worker(slot, ("reply", req_id, True, rids))
        return None

    # ------------------------------------------------------------------
    # two-level dispatch: p2p actor plane (tentpole b)
    # ------------------------------------------------------------------
    def _poll_peer_link(self, **ctx):
        """Chaos hook for the daemon-hosted peer_link site; fired
        injections are reported upward (report-class) so the head's
        injection log and counters stay cluster-wide."""
        from ray_tpu._private.chaos import get_controller

        ctrl = get_controller()
        if not ctrl.armed():
            return None
        fault = ctrl.poll("peer_link", **ctx)
        if fault is not None:
            log = ctrl.list_faults()
            entry = dict(log[-1]) if log else {
                "site": "peer_link", "kind": fault.get("kind")}
            self._send_head(("fault", entry))
        return fault

    def _request_route(self, aid_bin: bytes) -> None:
        now = time.monotonic()
        with self._p2p_lock:
            if now - self._aresolve_last.get(aid_bin, 0.0) < 0.5:
                return
            self._aresolve_last[aid_bin] = now
        self._send_head(("aresolve", aid_bin))

    def _on_aroute(self, aid_bin: bytes, route) -> None:
        with self._p2p_lock:
            if route is None:
                self._actor_routes.pop(aid_bin, None)
            else:
                self._actor_routes[aid_bin] = (
                    route[0], tuple(route[1]), route[2])

    def _mint_actor_task(self, aid_bin: bytes, num_returns: int):
        """Mint a p2p actor-call task id with the ActorHandle
        discipline (actor-id prefix + salted sequence) under a
        per-daemon random salt, so ids minted here collide with
        neither the head's handles nor another caller daemon's."""
        with self._p2p_lock:
            st = self._actor_salts.get(aid_bin)
            if st is None:
                st = self._actor_salts[aid_bin] = [
                    int.from_bytes(os.urandom(2), "big"), 0]
            st[1] += 1
            if st[1] > 0xFFFF:
                st[0] = int.from_bytes(os.urandom(2), "big")
                st[1] = 1
            seq = st[0] * 65536 + st[1]
        tid = TaskID.for_actor_task(ActorID(aid_bin), seq)
        rids = [ObjectID.for_task_return(tid, i).binary()
                for i in range(num_returns)]
        return tid.binary(), rids

    def _maybe_p2p_call(self, slot: _WorkerSlot, req_id: int,
                        args: tuple) -> Optional[tuple]:
        """P2P actor plane, caller side: a worker's actor call whose
        handle the head resolved to a peer (node, worker) address
        ships the call envelope DIRECTLY to that node's daemon over
        the peer link; the head sees only a sequenced completion
        receipt. No route yet / refs in the args / lane trouble — the
        unchanged head path."""
        fwd = ("rpc", req_id, "actor_call", args)
        if len(args) < 2 or args[1] is None:
            return fwd
        blob, meta = args[0], args[1]
        aid_bin, method, num_returns, trace, p2p_ok = meta
        with self._resview_lock:
            enabled = bool(self._resview.get("p2p"))
        if not enabled or not p2p_ok:
            return fwd
        with self._p2p_lock:
            route = self._actor_routes.get(aid_bin)
        if route is None:
            self._request_route(aid_bin)
            return fwd
        tid_bin, rids = self._mint_actor_task(aid_bin, num_returns)
        ctx = None
        if trace is not None and trace[3]:
            ctx = (trace[0], os.urandom(8).hex(), trace[1], True)
        with self._resview_lock:
            caller_node = self._resview.get("node")
        info = {"actor": aid_bin, "method": method, "blob": blob,
                "num_returns": num_returns, "returns": rids,
                "caller": slot.num, "caller_node": caller_node,
                "trace": ctx, "route": route,
                "t": time.monotonic(), "attempt": 0}
        with self._p2p_lock:
            self._p2p_calls[tid_bin] = info
        # the caller gets its return ids NOW: from here the call is
        # committed to the p2p lane or its exactly-once head fallback
        self._to_worker(slot, ("reply", req_id, True, rids))
        fault = self._poll_peer_link(actor=aid_bin.hex())
        if fault is not None:
            k = fault.get("kind")
            if k == "drop":
                self._fallback_call(tid_bin, "chaos: dropped call frame")
                return None
            if k == "sever":
                self._sever_lane(tuple(route[1]),
                                 "chaos: severed peer lane")
                return None
            time.sleep(fault.get("delay_s", 0.05))
        self._p2p_dispatch(tid_bin, info)
        return None

    def _p2p_dispatch(self, tid_bin: bytes, info: dict) -> None:
        from ray_tpu._private.task_spec import (EMPTY_ARGS_BLOB,
                                                encode_task_envelope)

        lane = self._actor_lane(tuple(info["route"][1]))
        if lane is None:
            self._fallback_call(tid_bin, "peer lane dial failed")
            return
        payload = {
            "task_id": tid_bin, "name": info["method"], "fn_id": None,
            "fn_blob": None, "args_blob": EMPTY_ARGS_BLOB,
            "num_returns": info["num_returns"],
            "return_ids": info["returns"],
            "attempt": info.get("attempt", 0),
            # extras: the executing worker unpickles the CALLER's blob
            # itself (only it has the user's modules); dedup marks the
            # completion cacheable for the exactly-once fallback
            "method": info["method"], "p2p_blob": info["blob"],
            "actor": info["actor"], "caller": info["caller"],
            "caller_node": info.get("caller_node"),
            "dedup": True,
        }
        if info.get("trace") is not None:
            payload["trace"] = info["trace"]
        key = (None, info["method"], info["num_returns"])
        with lane["lock"]:  # RLock: encode mutates the lane's caches
            env = encode_task_envelope(
                [(key, [payload])], lane["sent_fns"],
                lane["sent_hdrs"], lane["hdr_blobs"])
            if not self._lane_send(("acall", env), lane["conn"],
                                   lane["lock"]):
                self._drop_lane(lane, "peer lane send failed")

    def _lane_send(self, msg: tuple, conn, lock) -> bool:
        """The ONE send point for peer actor-lane frames (acall out,
        ares back) — wire-lint collects the channel's send set here."""
        try:
            with lock:
                conn.send(msg)
            return True
        except (OSError, ValueError):
            return False

    def _actor_lane(self, address) -> Optional[dict]:
        """Dial (or reuse) the dedicated actor-call lane to a peer
        daemon. Deliberately separate from the cached pull
        connections: chunked object streams and async call/result
        frames must not interleave on one pipe."""
        from multiprocessing import AuthenticationError

        from ray_tpu._private import protocol

        address = tuple(address)
        with self._p2p_lock:
            lane = self._p2p_lanes.get(address)
        if lane is not None:
            return lane
        try:
            conn = Client(address, authkey=self._peer_authkey)
            conn.send(protocol.make_wire_hello("peer"))
            if conn.recv() != ("ok",):
                conn.close()
                return None
        except (OSError, EOFError, ValueError, AuthenticationError):
            return None
        lane = {"conn": conn, "lock": threading.RLock(),
                "addr": address, "sent_fns": set(), "sent_hdrs": {},
                "hdr_blobs": {}}
        with self._p2p_lock:
            ex = self._p2p_lanes.get(address)
            if ex is not None:
                try:
                    conn.close()
                except Exception:
                    pass
                return ex
            self._p2p_lanes[address] = lane
        threading.Thread(target=self._lane_reader, args=(lane,),
                         daemon=True,
                         name="ray_tpu_actor_lane").start()
        return lane

    def _lane_reader(self, lane: dict) -> None:
        """Drain ("ares", ...) result frames off an actor lane; EOF
        (peer died, chaos sever) sweeps every in-flight call routed
        over it into the head-path fallback — same ids, exactly-once."""
        conn = lane["conn"]
        try:
            while not self._shutdown:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    break
                if not (isinstance(msg, tuple) and msg
                        and msg[0] == "ares"):
                    break
                self._on_ares(msg)
        finally:
            # a raise out of _on_ares (short frame, prefetch error) must
            # still tear the lane down — a reader-less lane would leave
            # every later call on this route to the slow timeout sweep
            self._drop_lane(lane, "peer lane lost")

    def _drop_lane(self, lane: dict, reason: str) -> None:
        addr = lane["addr"]
        with self._p2p_lock:
            if self._p2p_lanes.get(addr) is lane:
                del self._p2p_lanes[addr]
        try:
            lane["conn"].close()
        except Exception:
            pass
        self._sweep_route(addr, reason)

    def _sever_lane(self, address: tuple, reason: str) -> None:
        with self._p2p_lock:
            lane = self._p2p_lanes.get(tuple(address))
        if lane is not None:
            self._drop_lane(lane, reason)
        else:
            self._sweep_route(tuple(address), reason)

    def _sweep_route(self, address: tuple, reason: str) -> None:
        with self._p2p_lock:
            tids = [t for t, i in self._p2p_calls.items()
                    if tuple(i["route"][1]) == address]
        for t in tids:
            self._fallback_call(t, reason)

    def _on_ares(self, msg: tuple) -> None:
        _, tid_bin, status, data, _timing = msg
        with self._p2p_lock:
            info = self._p2p_calls.pop(tid_bin, None)
        if info is None:
            return  # already fell back (sweep won the race) — ignore
        if status == "miss":
            # stale route: the actor moved or its worker died there
            with self._p2p_lock:
                self._actor_routes.pop(info["actor"], None)
            self._fallback_call(tid_bin, "peer reported no such actor",
                                info)
            return
        if status == "done":
            peer = tuple(info["route"][1])
            for i, entry in enumerate(data or []):
                if not (isinstance(entry, tuple) and entry):
                    continue
                if (entry[0] == "remote_shm"
                        and i < len(info["returns"])):
                    # results return on the same link: pull the bytes
                    # from the executing peer at task-arg priority
                    self.pulls.prefetch(peer, info["returns"][i],
                                        PullManager.PRIO_ARG)
                elif entry[0] == "inline" and i < len(info["returns"]):
                    self._adopt_inline(info["returns"][i], entry[1])
        # err: nothing to localize — the head stores the exception
        # from the completion receipt and the caller's get resolves it

    def _adopt_inline(self, rid_bin: bytes, data: bytes) -> None:
        """Adopt an inline result into the local store so the caller's
        get is answered node-locally instead of via the head."""
        oid = ObjectID(rid_bin)
        if self.store.contains(oid):
            return
        try:
            kind, target = self.store.begin_adopt(oid, len(data))
        except Exception:
            return
        try:
            if kind == "arena":
                target[:len(data)] = data
            else:
                target.write(data)
        except Exception:
            if kind == "arena":
                target.release()
            self.store.abort_adopt(oid, kind,
                                   None if kind == "arena" else target)
            return
        if kind == "arena":
            target.release()
        self.store.finish_adopt(oid, len(data), kind,
                                None if kind == "arena" else target)

    def _fallback_call(self, tid_bin: bytes, reason: str,
                       info: Optional[dict] = None) -> None:
        """Re-route an in-flight p2p call through the head with the
        SAME task id / return ids / attempt token. The executing
        worker's dedup cache (p2p payloads carry dedup=True) re-emits
        the recorded completion if the peer actually ran the first
        attempt — bit-correct exactly-once, whichever half of the
        lane died."""
        if info is None:
            with self._p2p_lock:
                info = self._p2p_calls.pop(tid_bin, None)
        if info is None:
            return
        self._send_head(("p2p_fallback", tid_bin, {
            "actor": info["actor"], "method": info["method"],
            "blob": info["blob"], "num_returns": info["num_returns"],
            "returns": info["returns"], "caller": info["caller"],
            "trace": info["trace"], "attempt": info.get("attempt", 0),
            "reason": reason,
        }))

    def _on_peer_dead(self, info: dict) -> None:
        """Head broadcast: a peer node died. Evict every trace of it
        NOW — its gossip view (local admission must never trust a
        ghost node's resource/residency claims), cached p2p actor
        routes to its address, the lane itself, and every in-flight
        call routed over it (swept straight to the head-path fallback
        instead of waiting out the 15s p2p result timeout)."""
        addr = info.get("peer")
        addr = tuple(addr) if addr else None
        dead_index = info.get("index")
        if addr is not None:
            with self._p2p_lock:
                self._dead_peers.add(addr)
            with self._resview_lock:
                peers = self._resview.get("peers")
                if peers:
                    self._resview["peers"] = [
                        p for p in peers if tuple(p) != addr]
        with self._p2p_lock:
            stale = [aid for aid, route in self._actor_routes.items()
                     if (addr is not None and tuple(route[1]) == addr)
                     or (dead_index is not None
                         and route[0] == dead_index)]
            for aid in stale:
                del self._actor_routes[aid]
        if addr is not None:
            self._sever_lane(addr, "peer node died")

    def _on_fence(self, epoch) -> None:
        """The head re-adopted this daemon AFTER declaring its node
        dead: everything from the dead era was already resubmitted or
        failed head-side, so clear the local-lease bodies (no zombie
        re-lease), the in-flight p2p call table (no stale head
        fallback re-executing a settled call), and the outbox (its
        replays were acked-and-dropped by the fenced pool anyway)."""
        import logging

        # _local_leases is GIL-atomic like its other mutation sites
        # (worker reader threads pop, admission assigns — none hold a
        # lock); only the _local_tids admission set is _resview_lock'd
        n_leases = len(self._local_leases)
        self._local_leases.clear()
        with self._resview_lock:
            self._local_tids.clear()
        with self._p2p_lock:
            n_calls = len(self._p2p_calls)
            self._p2p_calls.clear()
        self._outbox.ack(self._outbox.last_seq)
        logging.getLogger(__name__).warning(
            "fenced by head (epoch %s): cleared %d dead-era local "
            "leases and %d in-flight p2p calls", epoch, n_leases,
            n_calls)

    def _gossip_loop(self) -> None:
        """Tentpole (d): re-share the freshest resource view this
        daemon holds with its peers over the existing actor lanes, so
        every node's local admission stays current when the head is
        slow, blacked out, or mid-rejoin. Versioned adoption (epoch +
        strictly-newer v, see _apply_resview) keeps the head the
        authoritative tiebreaker."""
        from ray_tpu._private.config import GLOBAL_CONFIG

        while not self._shutdown:
            period = float(GLOBAL_CONFIG.resview_gossip_s)
            time.sleep(period if period > 0 else 1.0)
            if period <= 0:
                continue
            with self._resview_lock:
                view = dict(self._resview)
            if not (view.get("accept") or view.get("p2p")):
                continue  # knobs off: the peer wire stays silent
            # origin stamp: receivers drop views gossiped FROM a node
            # the head has since declared dead (ghost-view eviction)
            view["from"] = tuple(self.peer_address)
            for addr in view.get("peers") or ():
                with self._p2p_lock:
                    if tuple(addr) in self._dead_peers:
                        continue
                # the gossip frames ride the same peer lanes as p2p
                # calls, so the peer_link chaos site covers them too:
                # a severed/dropped lane must cost only freshness (the
                # next tick redials), never correctness
                fault = self._poll_peer_link(frame="rview")
                if fault is not None:
                    k = fault.get("kind")
                    if k == "sever":
                        self._sever_lane(tuple(addr),
                                         "chaos: severed gossip lane")
                        continue
                    if k == "drop":
                        continue
                    time.sleep(fault.get("delay_s", 0.05))
                lane = self._actor_lane(addr)
                if lane is None:
                    continue
                if not self._lane_send(("rview", view), lane["conn"],
                                       lane["lock"]):
                    self._drop_lane(lane, "peer lane send failed")

    def _p2p_sweep_loop(self) -> None:
        """Safety net under the lane-EOF sweep: a call whose result
        frame never arrives (peer wedged, frame lost to a half-dead
        socket) falls back through the head after a generous timeout."""
        while not self._shutdown:
            time.sleep(1.0)
            now = time.monotonic()
            with self._p2p_lock:
                stale = [t for t, i in self._p2p_calls.items()
                         if now - i["t"] > 15.0]
            for t in stale:
                self._fallback_call(t, "p2p result timed out")

    def _serve_acall(self, conn, send_lock, hdr_cache: Dict[int, tuple],
                     env_blob: bytes) -> None:
        """Executing side of the p2p lane: decode the lease envelope,
        dispatch each call to the resident dedicated actor worker, and
        remember the lane so the completion goes back on it. A call
        for an actor that does not live here (stale route) answers
        ("ares", tid, "miss", ...) so the caller re-resolves."""
        from ray_tpu._private.task_spec import decode_task_envelope

        try:
            payloads = decode_task_envelope(env_blob, hdr_cache)
        except Exception:
            return
        for p in payloads:
            tid_bin = p["task_id"]
            aid_bin = p.get("actor")
            with self._lock:
                slot = next(
                    (s for s in self._slots.values()
                     if aid_bin is not None and s.actor_bin == aid_bin
                     and s.conn is not None), None)
            if slot is None or (slot.proc is not None
                                and slot.proc.poll() is not None):
                self._lane_send(("ares", tid_bin, "miss", None, None),
                                conn, send_lock)
                continue
            info = {"actor": aid_bin, "caller": p.get("caller"),
                    "caller_node": p.get("caller_node"),
                    "method": p.get("method"), "name": p.get("name"),
                    "trace": p.get("trace")}
            slot.returns[tid_bin] = list(p["return_ids"])
            slot.attempts[tid_bin] = p.get("attempt", 0)
            with self._p2p_lock:
                self._p2p_pending[tid_bin] = (conn, send_lock, info)
            self._to_worker(slot, ("actor_call", p))

    def _finish_p2p_exec(self, slot: _WorkerSlot, tid_bin: bytes,
                         p2p: tuple, msg: tuple) -> None:
        """A peer-dispatched call finished on THIS node: the head gets
        its (report-class) completion receipt, then the result frames
        go back over the lane the call arrived on. Receipt first and
        always — a dead lane just means the caller's daemon falls
        back, and the worker-side dedup cache keeps that retry
        exactly-once."""
        conn, send_lock, info = p2p
        return_bins = slot.returns.pop(tid_bin, [])
        slot.attempts.pop(tid_bin, None)
        receipt = {"actor": info.get("actor"),
                   "method": info.get("method"),
                   "name": info.get("name"),
                   "caller": info.get("caller"),
                   "caller_node": info.get("caller_node"),
                   "trace": info.get("trace"),
                   "worker_num": slot.num, "returns": return_bins}
        if msg[0] == "done":
            out = []
            for i, entry in enumerate(msg[2]):
                if entry[0] == "shm" and i < len(return_bins):
                    rid = ObjectID(return_bins[i])
                    if self.store.locate(rid) is None:
                        self.store.seal(rid)
                    out.append(("remote_shm", entry[2]))
                else:
                    out.append(entry)
            timing = msg[3] if len(msg) > 3 else None
            receipt["entries"] = out
            receipt["timing"] = timing
            self._send_head(("p2p_done", tid_bin, receipt))
            self._lane_send(("ares", tid_bin, "done", out, timing),
                            conn, send_lock)
        else:
            timing = msg[4] if len(msg) > 4 else None
            receipt["err"] = (msg[2], msg[3])
            receipt["timing"] = timing
            self._send_head(("p2p_done", tid_bin, receipt))
            self._lane_send(("ares", tid_bin, "err",
                             (msg[2], msg[3]), timing),
                            conn, send_lock)

    def _register_lease_msg(self, slot: _WorkerSlot, msg: tuple) -> None:
        """Bookkeeping copy of a head->worker lease in transit: record
        return ids + attempt tokens per worker so a rejoin hello can
        report exactly what is still running here. Registered as an
        extra recv of the raylint owner_to_worker channel — the daemon
        decodes the SAME frames the worker does, including the remote
        lease envelope (tentpole c), so schema drift on the relayed
        channel is caught here too."""
        if msg[0] in ("task", "actor_create", "actor_call"):
            p = msg[1]
            rids = p.get("return_ids")
            if rids:
                slot.returns[p["task_id"]] = list(rids)
                slot.attempts[p["task_id"]] = p.get("attempt", 0)
            if msg[0] == "actor_create":
                slot.actor_bin = p.get("actor_bin")
        elif msg[0] == "tasks":
            for p in msg[1]:
                rids = p.get("return_ids")
                if rids:
                    slot.returns[p["task_id"]] = list(rids)
                    slot.attempts[p["task_id"]] = p.get("attempt", 0)
        elif msg[0] == "env":
            # remote lease envelope: decode a copy for the per-worker
            # bookkeeping, forward the blob verbatim — the worker's own
            # header cache evolves in lockstep off the same stream
            from ray_tpu._private.task_spec import decode_task_envelope

            for p in decode_task_envelope(msg[1], slot.hdr_cache):
                rids = p.get("return_ids")
                if rids:
                    slot.returns[p["task_id"]] = list(rids)
                    slot.attempts[p["task_id"]] = p.get("attempt", 0)

    # ------------------------------------------------------------------
    # head -> daemon main loop
    # ------------------------------------------------------------------
    def run(self) -> None:
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="ray_tpu_node_accept").start()
        threading.Thread(target=self._log_tail_loop, daemon=True,
                         name="ray_tpu_node_log_tail").start()
        threading.Thread(target=self._p2p_sweep_loop, daemon=True,
                         name="ray_tpu_node_p2p_sweep").start()
        threading.Thread(target=self._gossip_loop, daemon=True,
                         name="ray_tpu_node_resview_gossip").start()
        self._start_util_sampler()
        while not self._shutdown:
            try:
                msg = self._head.recv()
            except (EOFError, OSError):
                # head gone WITHOUT an exit: orphaned. Try to rejoin a
                # restarted head at the same address; workers (and the
                # actor state inside them) stay alive meanwhile.
                import logging
                logging.getLogger(__name__).warning(
                    "head connection lost; trying to rejoin %s for %.0fs",
                    self._head_address, self._rejoin_timeout_s)
                if self._rejoin_timeout_s > 0 and self._try_rejoin():
                    logging.getLogger(__name__).warning(
                        "rejoined head at %s; workers survived",
                        self._head_address)
                    continue
                break  # no head came back: the node dies
            runtime_sanitizer.check_wire("head_to_daemon", msg)
            kind = msg[0]
            if kind == "error":
                # e.g. protocol-version rejection of our hello: the
                # head told us WHY — log it and die instead of retrying
                import logging
                logging.getLogger(__name__).error(
                    "head rejected this node: %s", msg[1])
                break
            if kind == "spawn":
                self._spawn(msg[1], msg[2] if len(msg) > 2 else None)
            elif kind == "to_w":
                num, payload = msg[1], msg[2]
                with self._lock:
                    slot = self._slots.get(num)
                if slot is not None and slot.conn is not None:
                    self._register_lease_msg(slot, payload)
                    if (payload[0] == "reply"
                            and payload[1] in slot.gets):
                        purpose = slot.gets.pop(payload[1])
                        if payload[2]:
                            prio = (PullManager.PRIO_ARG
                                    if purpose == "arg"
                                    else PullManager.PRIO_GET)
                            locs = payload[3]
                            if any(isinstance(lc, tuple) and lc
                                   and lc[0] == "peer" for lc in locs):
                                # peer pulls can take seconds: NEVER on
                                # the head-message run loop (it carries
                                # task dispatch + pings for the node)
                                threading.Thread(
                                    target=self._localize_reply,
                                    args=(slot, payload[1], locs, prio),
                                    daemon=True).start()
                                continue
                            payload = ("reply", payload[1], True,
                                       [self._localize(lc, prio)
                                        for lc in locs])
                    self._to_worker(slot, payload)
            elif kind == "to_ctrl":
                with self._lock:
                    slot = self._slots.get(msg[1])
                if slot is not None and slot.ctrl is not None:
                    try:
                        slot.ctrl.send(msg[2])
                    except (OSError, ValueError):
                        pass
            elif kind == "kill":
                with self._lock:
                    slot = self._slots.get(msg[1])
                if slot is not None and slot.proc is not None:
                    try:
                        slot.proc.kill()
                    except Exception:
                        pass
            elif kind == "fetch":
                # off the run loop: serializing + sending a large object
                # must not stall task dispatch / pings for the node
                # (sends are serialized by _head_lock)
                threading.Thread(
                    target=self._serve_fetch, args=(msg[1], msg[2]),
                    daemon=True, name="ray_tpu_node_fetch").start()
            elif kind == "log_list":
                # off the run loop, like fetch: disk reads must not
                # stall task dispatch for the node
                threading.Thread(
                    target=self._serve_log_list, args=(msg[1],),
                    daemon=True, name="ray_tpu_node_log_list").start()
            elif kind == "log_read":
                threading.Thread(
                    target=self._serve_log_read,
                    args=(msg[1], msg[2], msg[3]),
                    daemon=True, name="ray_tpu_node_log_read").start()
            elif kind == "stage":
                # dispatch-time arg staging: enqueue peer pulls NOW at
                # task-arg priority so transfers overlap the lease's
                # queue wait; completions report ("pulled", oid) and
                # the exec-time localization finds the bytes resident
                for oid_bin, address, _nbytes in msg[1]:
                    self.pulls.prefetch(address, oid_bin,
                                        PullManager.PRIO_ARG)
            elif kind == "resview":
                self._apply_resview(msg[1])
            elif kind == "aroute":
                self._on_aroute(msg[1], msg[2])
            elif kind == "node_dead":
                self._on_peer_dead(msg[1])
            elif kind == "fence":
                self._on_fence(msg[1])
            elif kind == "free":
                for b in msg[1]:
                    self.store.free_object(ObjectID(b))
            elif kind == "ping":
                with self._lock:
                    pids = {s.num: s.pid for s in self._slots.values()
                            if s.proc is not None and s.proc.poll() is None}
                self._send_head(("pong", msg[1], pids))
            elif kind == "ack":
                # outbox high-water acknowledgment: the head processed
                # (or deduped) every report up to this sequence number
                self._outbox.ack(msg[1])
            elif kind == "exit":
                break
            else:
                # exhaustive dispatch: a tag this daemon doesn't know
                # means head/daemon version (or protocol) drift — fail
                # loudly instead of silently dropping control messages
                import logging
                logging.getLogger(__name__).error(
                    "node daemon: unknown head message tag %r "
                    "(protocol drift? head and node running different "
                    "versions)", kind)
        self.shutdown()

    def _try_rejoin(self) -> bool:
        """Re-dial the head address until a (restarted) head accepts
        this node back. The rejoin hello reports the live workers —
        numbers, pids, which actor each dedicated worker hosts, and
        every task still IN FLIGHT (task id -> return oids + attempt
        token) — so the new head re-adopts them, re-attaches the live
        leases to their waiting ObjectRefs, and resubmits only what no
        surviving node claims. Work is never pre-killed here: whether
        an in-flight lease is still wanted is the HEAD's call (lease
        reconciliation), not this daemon's."""
        import time

        deadline = time.monotonic() + self._rejoin_timeout_s
        while not self._shutdown and time.monotonic() < deadline:
            try:
                head = Client(self._head_address,
                              authkey=self._head_authkey)
            except Exception:  # conn refused / auth failure / reset
                time.sleep(0.5)
                continue
            # p2p-pending executions are excluded from the in-flight
            # report: their completion reaches the head as a
            # self-contained ("p2p_done", ...) receipt, so the new head
            # must not also adopt a lease it would wait on forever
            with self._p2p_lock:
                p2p_tids = set(self._p2p_pending)
            with self._lock:
                workers = {
                    s.num: {"pid": s.pid,
                            "actor": (s.actor_bin.hex()
                                      if s.actor_bin else None),
                            "inflight": {
                                tid.hex(): {
                                    "returns": [b.hex() for b in rbins],
                                    "attempt": s.attempts.get(tid, 0),
                                }
                                for tid, rbins in s.returns.items()
                                if tid not in p2p_tids}}
                    for s in self._slots.values()
                    if s.proc is not None and s.proc.poll() is None}
            from ray_tpu._private.protocol import make_wire_hello

            try:
                head.send(make_wire_hello(
                    "rejoin", os.getpid(), self.store.arena.name,
                    dict(self._node_info), tuple(self.peer_address),
                    workers))
            except (OSError, ValueError):
                try:
                    head.close()
                except Exception:
                    pass
                time.sleep(0.5)
                continue
            with self._head_lock:
                self._head = head
            # re-run the clock handshake: the new head computes a fresh
            # clock_offset for this link
            self._send_head_raw(("clock", time.time(),
                                 time.perf_counter()))
            # replay every unacked report: completions/pulls/logs that
            # happened during the blackout reach the new head now; the
            # head's per-node sequence dedup makes this exactly-once
            # even when the old head never actually died (link flap)
            self._replay_outbox()
            return True
        return False

    def shutdown(self) -> None:
        self._shutdown = True
        sampler = getattr(self, "_util_sampler", None)
        if sampler is not None:
            sampler.stop()
        with self._lock:
            slots = list(self._slots.values())
        for s in slots:
            if s.conn is not None:
                try:
                    s.conn.send(("exit",))
                except (OSError, ValueError):
                    pass
        for s in slots:
            if s.proc is not None:
                try:
                    s.proc.wait(timeout=1.0)
                except subprocess.TimeoutExpired:
                    s.proc.kill()
        try:
            self._listener.close()
        except Exception:
            pass
        try:
            self._peer_listener.close()
        except Exception:
            pass
        with self._peer_lock:
            entries, self._peer_conns = list(self._peer_conns.values()), {}
        for entry in entries:
            try:
                if entry[0] is not None:
                    entry[0].close()
            except Exception:
                pass
        with self._p2p_lock:
            lanes, self._p2p_lanes = list(self._p2p_lanes.values()), {}
        for lane in lanes:
            try:
                lane["conn"].close()
            except Exception:
                pass
        try:
            os.rmdir(self._sock_dir)
        except OSError:
            pass
        self.store.shutdown()


def _main(argv) -> None:
    """``python -m ray_tpu._private.runtime.node_daemon <host> <port>
    <token> <object_store_memory> <inline_max> [join_info_json]
    [rejoin_timeout_s]`` with the head authkey in
    RAY_TPU_HEAD_AUTHKEY. Exec'd by the head's Cluster harness, or
    self-started with token "join" by `ray_tpu start --address=...`
    on another machine."""
    import json

    # capture this daemon's own stdout/stderr first (dup2) when the
    # spawner asked for it — import/startup failures land in the file
    from ray_tpu._private import log_plane

    log_plane.redirect_stdio_from_env()

    host, port, token = argv[0], int(argv[1]), argv[2]
    mem, inline_max = int(argv[3]), int(argv[4])
    join_info = (json.loads(argv[5])
                 if len(argv) > 5 and argv[5] else None)
    rejoin = float(argv[6]) if len(argv) > 6 else 20.0
    authkey = bytes.fromhex(os.environ["RAY_TPU_HEAD_AUTHKEY"])
    daemon = NodeDaemon((host, port), authkey, token, mem, inline_max,
                        join_info=join_info, rejoin_timeout_s=rejoin)
    daemon.run()


if __name__ == "__main__":
    # canonical-import re-entry (same reason as worker_process.py)
    from ray_tpu._private.runtime import node_daemon as _canonical

    _canonical._main(sys.argv[1:])
