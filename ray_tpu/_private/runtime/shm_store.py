"""Shared-memory object store — the plasma analog.

Reference surface: ray src/ray/object_manager/plasma/ (PlasmaStore,
ObjectLifecycleManager, PlasmaClient): a per-node shared-memory arena,
objects written once through a create -> seal lifecycle, then read
zero-copy by any process on the node via mmap.

TPU-native differences: one mmap arena per node owned by the driver
process (the node owner); allocation decisions are made owner-side only
(workers request offsets over their pipe — the create/seal RPC), while
reads and writes go straight through each process's own mapping of the
arena, so object BYTES never cross a pipe. Deserialization wraps numpy
buffers around the arena memory (zero-copy views, valid while the object
is in scope — the same contract as plasma's read-only buffers).
"""

from __future__ import annotations

import threading
from multiprocessing import shared_memory
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStoreFullError
from ray_tpu._private.serialization import SerializedObject

_ALIGN = 64  # cache-line align allocations


class PyFreeList:
    """Pure-Python first-fit free list (the fallback when the native
    C++ allocator in ray_tpu/_native/allocator.cc can't build/load;
    identical first-fit-by-offset semantics, parity-tested)."""

    def __init__(self, size: int, align: int = _ALIGN):
        self._align = align
        self._free: List[Tuple[int, int]] = [(0, size)]

    def _round(self, nbytes: int) -> int:
        a = self._align
        return max(a, (nbytes + a - 1) & ~(a - 1))

    def allocate(self, nbytes: int) -> int:
        nbytes = self._round(nbytes)
        for i, (off, sz) in enumerate(self._free):
            if sz >= nbytes:
                if sz == nbytes:
                    self._free.pop(i)
                else:
                    self._free[i] = (off + nbytes, sz - nbytes)
                return off
        return -1

    def free(self, offset: int, nbytes: int) -> None:
        nbytes = self._round(nbytes)
        free = self._free
        lo, hi = 0, len(free)
        while lo < hi:
            mid = (lo + hi) // 2
            if free[mid][0] < offset:
                lo = mid + 1
            else:
                hi = mid
        # overlap detection mirrors the native allocator: a double free
        # must raise, not silently corrupt the free list
        if lo < len(free) and offset + nbytes > free[lo][0]:
            raise ValueError(
                f"invalid free: [{offset}, {offset + nbytes}) overlaps "
                "an existing hole (double free?)")
        if lo > 0:
            po, ps = free[lo - 1]
            if po + ps > offset:
                raise ValueError(
                    f"invalid free: [{offset}, {offset + nbytes}) "
                    "overlaps an existing hole (double free?)")
        free.insert(lo, (offset, nbytes))
        if lo + 1 < len(free):
            o, s = free[lo]
            o2, s2 = free[lo + 1]
            if o + s == o2:
                free[lo] = (o, s + s2)
                free.pop(lo + 1)
        if lo > 0:
            o, s = free[lo - 1]
            o2, s2 = free[lo]
            if o + s == o2:
                free[lo - 1] = (o, s + s2)
                free.pop(lo)

    def free_bytes(self) -> int:
        return sum(s for _, s in self._free)

    def num_holes(self) -> int:
        return len(self._free)


def make_free_list(size: int, align: int = _ALIGN):
    """Native C++ allocator when buildable, Python fallback otherwise."""
    try:
        from ray_tpu._native import NativeFreeList

        return NativeFreeList(size, align)
    except ImportError:
        return PyFreeList(size, align)


class ShmArena:
    """A named shared-memory segment + free-list allocator (native C++
    core via ray_tpu/_native, Python fallback).

    The allocator lives ONLY in the owner process; attached clients
    (worker processes) are handed (offset, size) pairs and use views.
    """

    def __init__(self, size: int, name: Optional[str] = None,
                 create: bool = True):
        self._shm = shared_memory.SharedMemory(
            name=name, create=create, size=size if create else 0)
        if not create:
            # Python <=3.12 registers attached segments with the
            # resource_tracker, which UNLINKS them when the attaching
            # process exits — a worker exiting would destroy the node's
            # arena under the driver. The owner is responsible for unlink.
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self._shm._name,  # noqa: SLF001
                                            "shared_memory")
            except Exception:
                pass
        self.name = self._shm.name
        self.size = self._shm.size
        self._owner = create
        self._alloc = make_free_list(self.size) if create else None
        self._lock = threading.Lock()

    @classmethod
    def attach(cls, name: str) -> "ShmArena":
        return cls(0, name=name, create=False)

    # -- allocator (owner side only) ---------------------------------------
    def allocate(self, nbytes: int) -> int:
        if self._alloc is None:
            raise RuntimeError("allocate() on an ATTACHED arena: only "
                               "the owner process allocates; clients "
                               "request offsets over the create RPC")
        with self._lock:
            off = self._alloc.allocate(nbytes)
            if off >= 0:
                return off
            raise ObjectStoreFullError(
                f"shm arena full: requested {nbytes} bytes, "
                f"{self._alloc.free_bytes()} free (fragmented across "
                f"{self._alloc.num_holes()} holes)")

    def free(self, offset: int, nbytes: int) -> None:
        if self._alloc is None:
            raise RuntimeError("free() on an ATTACHED arena: only the "
                               "owner process manages allocations")
        with self._lock:
            self._alloc.free(offset, nbytes)

    def free_bytes(self) -> int:
        if self._alloc is None:
            return 0  # attached client: no allocator view
        with self._lock:
            return self._alloc.free_bytes()

    # -- data access (any process) -----------------------------------------
    def view(self, offset: int, nbytes: int) -> memoryview:
        return self._shm.buf[offset:offset + nbytes]

    def close(self) -> None:
        try:
            self._shm.close()
        except BufferError:
            # exported zero-copy views still alive (user holds arrays);
            # the mapping stays until they are collected
            pass

    def unlink(self) -> None:
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


class _Alloc:
    __slots__ = ("offset", "nbytes", "sealed")

    def __init__(self, offset: int, nbytes: int):
        self.offset = offset
        self.nbytes = nbytes
        self.sealed = False


class ShmObjectStore:
    """Owner-side object table over a ShmArena: create/seal/locate/free.

    Reference: plasma's ObjectLifecycleManager — an object is writable
    between create and seal, immutable and readable after seal.
    """

    def __init__(self, capacity_bytes: int):
        self.arena = ShmArena(capacity_bytes)
        self._table: Dict[ObjectID, _Alloc] = {}
        self._lock = threading.Lock()

    # -- create/seal lifecycle --------------------------------------------
    def create(self, object_id: ObjectID, nbytes: int) -> int:
        offset = self.arena.allocate(nbytes)
        with self._lock:
            if object_id in self._table:
                self.arena.free(offset, nbytes)
                raise ValueError(f"object {object_id.hex()} already created")
            self._table[object_id] = _Alloc(offset, nbytes)
        return offset

    def seal(self, object_id: ObjectID) -> None:
        with self._lock:
            self._table[object_id].sealed = True

    def locate(self, object_id: ObjectID) -> Optional[Tuple[int, int]]:
        """(offset, nbytes) of a SEALED object, else None."""
        with self._lock:
            alloc = self._table.get(object_id)
            if alloc is None or not alloc.sealed:
                return None
            return alloc.offset, alloc.nbytes

    def contains(self, object_id: ObjectID) -> bool:
        return self.locate(object_id) is not None

    # -- owner-process direct IO ------------------------------------------
    def put_serialized(self, object_id: ObjectID,
                       sobj: SerializedObject) -> Tuple[int, int]:
        """create + write + seal in the owner process (driver puts)."""
        nbytes = sobj.framed_nbytes()
        offset = self.create(object_id, nbytes)
        sobj.write_into(self.arena.view(offset, nbytes))
        self.seal(object_id)
        return offset, nbytes

    def get_serialized(self, object_id: ObjectID) -> Optional[SerializedObject]:
        loc = self.locate(object_id)
        if loc is None:
            return None
        offset, nbytes = loc
        return SerializedObject.from_bytes(self.arena.view(offset, nbytes))

    def free_object(self, object_id: ObjectID) -> None:
        with self._lock:
            alloc = self._table.pop(object_id, None)
        if alloc is not None:
            self.arena.free(alloc.offset, alloc.nbytes)

    # -- stats / lifecycle -------------------------------------------------
    def num_objects(self) -> int:
        with self._lock:
            return len(self._table)

    def used_bytes(self) -> int:
        return self.arena.size - self.arena.free_bytes()

    def shutdown(self) -> None:
        self.arena.close()
        self.arena.unlink()
