"""Shared-memory object store — the plasma analog.

Reference surface: ray src/ray/object_manager/plasma/ (PlasmaStore,
ObjectLifecycleManager, PlasmaClient): a per-node shared-memory arena,
objects written once through a create -> seal lifecycle, then read
zero-copy by any process on the node via mmap.

TPU-native differences: one mmap arena per node owned by the driver
process (the node owner); allocation decisions are made owner-side only
(workers request offsets over their pipe — the create/seal RPC), while
reads and writes go straight through each process's own mapping of the
arena, so object BYTES never cross a pipe. Deserialization wraps numpy
buffers around the arena memory (zero-copy views, valid while the object
is in scope — the same contract as plasma's read-only buffers).
"""

from __future__ import annotations

import os
import shutil
import struct
import tempfile
import threading
from multiprocessing import shared_memory
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.analysis import runtime_sanitizer
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStoreFullError
from ray_tpu._private.serialization import SerializedObject

_ALIGN = 64  # cache-line align allocations


class PyFreeList:
    """Pure-Python first-fit free list (the fallback when the native
    C++ allocator in ray_tpu/_native/allocator.cc can't build/load;
    identical first-fit-by-offset semantics, parity-tested)."""

    def __init__(self, size: int, align: int = _ALIGN):
        self._align = align
        self._free: List[Tuple[int, int]] = [(0, size)]

    def _round(self, nbytes: int) -> int:
        a = self._align
        return max(a, (nbytes + a - 1) & ~(a - 1))

    def allocate(self, nbytes: int) -> int:
        nbytes = self._round(nbytes)
        for i, (off, sz) in enumerate(self._free):
            if sz >= nbytes:
                if sz == nbytes:
                    self._free.pop(i)
                else:
                    self._free[i] = (off + nbytes, sz - nbytes)
                return off
        return -1

    def free(self, offset: int, nbytes: int) -> None:
        nbytes = self._round(nbytes)
        free = self._free
        lo, hi = 0, len(free)
        while lo < hi:
            mid = (lo + hi) // 2
            if free[mid][0] < offset:
                lo = mid + 1
            else:
                hi = mid
        # overlap detection mirrors the native allocator: a double free
        # must raise, not silently corrupt the free list
        if lo < len(free) and offset + nbytes > free[lo][0]:
            raise ValueError(
                f"invalid free: [{offset}, {offset + nbytes}) overlaps "
                "an existing hole (double free?)")
        if lo > 0:
            po, ps = free[lo - 1]
            if po + ps > offset:
                raise ValueError(
                    f"invalid free: [{offset}, {offset + nbytes}) "
                    "overlaps an existing hole (double free?)")
        free.insert(lo, (offset, nbytes))
        if lo + 1 < len(free):
            o, s = free[lo]
            o2, s2 = free[lo + 1]
            if o + s == o2:
                free[lo] = (o, s + s2)
                free.pop(lo + 1)
        if lo > 0:
            o, s = free[lo - 1]
            o2, s2 = free[lo]
            if o + s == o2:
                free[lo - 1] = (o, s + s2)
                free.pop(lo)

    def free_bytes(self) -> int:
        return sum(s for _, s in self._free)

    def num_holes(self) -> int:
        return len(self._free)


def make_free_list(size: int, align: int = _ALIGN):
    """Native C++ allocator when buildable, Python fallback otherwise."""
    try:
        from ray_tpu._native import NativeFreeList

        return NativeFreeList(size, align)
    except ImportError:
        return PyFreeList(size, align)


class _QuietSharedMemory(shared_memory.SharedMemory):
    """SharedMemory whose finalizer tolerates live zero-copy exports.

    A user legitimately holding an arena-backed array (Arrow column,
    numpy view) past store shutdown makes mmap.close() raise
    BufferError; stdlib __del__ re-raises it as an unraisable warning
    on every GC. The mapping simply stays until the views die (the OS
    reclaims at process exit either way) — that's the documented
    zero-copy contract, not an error."""

    def __del__(self):
        try:
            super().__del__()
        except BufferError:
            pass


class ShmArena:
    """A named shared-memory segment + free-list allocator (native C++
    core via ray_tpu/_native, Python fallback).

    The allocator lives ONLY in the owner process; attached clients
    (worker processes) are handed (offset, size) pairs and use views.
    """

    def __init__(self, size: int, name: Optional[str] = None,
                 create: bool = True):
        self._shm = _QuietSharedMemory(
            name=name, create=create, size=size if create else 0)
        if not create:
            # Python <=3.12 registers attached segments with the
            # resource_tracker, which UNLINKS them when the attaching
            # process exits — a worker exiting would destroy the node's
            # arena under the driver. The owner is responsible for unlink.
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self._shm._name,  # noqa: SLF001
                                            "shared_memory")
            except Exception:
                pass
        self.name = self._shm.name
        self.size = self._shm.size
        self._owner = create
        self._alloc = make_free_list(self.size) if create else None
        self._lock = runtime_sanitizer.wrap_lock(
            threading.Lock(), "_private.runtime.shm_store.ShmArena._lock")

    @classmethod
    def attach(cls, name: str) -> "ShmArena":
        return cls(0, name=name, create=False)

    # -- allocator (owner side only) ---------------------------------------
    def allocate(self, nbytes: int) -> int:
        if self._alloc is None:
            raise RuntimeError("allocate() on an ATTACHED arena: only "
                               "the owner process allocates; clients "
                               "request offsets over the create RPC")
        with self._lock:
            off = self._alloc.allocate(nbytes)
            if off >= 0:
                return off
            raise ObjectStoreFullError(
                f"shm arena full: requested {nbytes} bytes, "
                f"{self._alloc.free_bytes()} free (fragmented across "
                f"{self._alloc.num_holes()} holes)")

    def free(self, offset: int, nbytes: int) -> None:
        if self._alloc is None:
            raise RuntimeError("free() on an ATTACHED arena: only the "
                               "owner process manages allocations")
        with self._lock:
            self._alloc.free(offset, nbytes)

    def free_bytes(self) -> int:
        if self._alloc is None:
            return 0  # attached client: no allocator view
        with self._lock:
            return self._alloc.free_bytes()

    # -- data access (any process) -----------------------------------------
    def view(self, offset: int, nbytes: int) -> memoryview:
        return self._shm.buf[offset:offset + nbytes]

    def close(self) -> None:
        try:
            self._shm.close()
        except BufferError:
            # exported zero-copy views still alive (user holds arrays);
            # the mapping stays until they are collected
            pass

    def unlink(self) -> None:
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


# one tag byte prefixes every ring slot so both directions stay
# self-describing (and the runtime wire sanitizer can reconstruct the
# tagged-tuple form a pipe would have carried)
RING_TAGS = {1: "env", 2: "cenv"}
RING_TAG_BYTE = {name: bytes([code]) for code, name in RING_TAGS.items()}


class ControlRing:
    """Fixed-slot SPSC ring over a region of the shm arena — the
    control-plane sibling of the data-plane object store.

    Reference surface: LMAX-disruptor-style sequence stamping. Layout:
    a 128-byte header (two cache lines: producer cursor at +0, consumer
    cursor at +64) followed by ``nslots`` slots of ``slot_bytes`` each;
    a slot is ``[seq u32][len u32][payload]``. The producer writes the
    payload and length first and publishes by storing the slot's
    sequence stamp LAST — a single aligned 4-byte store, so the
    consumer observes either the whole message or none of it (x86/ARM
    release-on-store is sufficient for SPSC; the pipe doorbell that
    follows every put provides the cross-core ordering hop anyway).

    Strictly single-producer / single-consumer: the owner serializes
    producers with the handle's send lock, the worker consumes from its
    main thread only. Messages never span slots — anything larger than
    ``max_msg`` is the caller's cue to fall back to the pipe.
    """

    HEADER = 128
    _U32 = struct.Struct("<I")

    def __init__(self, arena: "ShmArena", offset: int, nslots: int,
                 slot_bytes: int, create: bool = False):
        self.offset = offset
        self.nslots = nslots
        self.slot_bytes = slot_bytes
        self.max_msg = slot_bytes - 8
        self._buf = arena.view(offset, self.region_bytes(nslots,
                                                         slot_bytes))
        self._wseq = 0  # producer-local cursor
        self._rseq = 0  # consumer-local cursor
        if create:
            # zero cursors AND every slot stamp: the region may be
            # recycled from the arena free list, and a stale stamp
            # equal to an expected sequence would replay garbage
            u32, buf = self._U32, self._buf
            u32.pack_into(buf, 0, 0)
            u32.pack_into(buf, 64, 0)
            for i in range(nslots):
                u32.pack_into(buf, self.HEADER + i * slot_bytes, 0)

    @classmethod
    def region_bytes(cls, nslots: int, slot_bytes: int) -> int:
        return cls.HEADER + nslots * slot_bytes

    def try_put(self, data) -> bool:
        """Publish one message; False = full or oversized (caller falls
        back to the pipe). Producer side only."""
        n = len(data)
        if n > self.max_msg:
            return False
        u32, buf = self._U32, self._buf
        w = self._wseq
        if ((w - u32.unpack_from(buf, 64)[0]) & 0xFFFFFFFF) >= self.nslots:
            return False  # consumer hasn't released the oldest slot
        off = self.HEADER + (w % self.nslots) * self.slot_bytes
        buf[off + 8:off + 8 + n] = data
        u32.pack_into(buf, off + 4, n)
        seq = (w + 1) & 0xFFFFFFFF
        u32.pack_into(buf, off, seq)       # publish: stamp goes last
        u32.pack_into(buf, 0, seq)         # advertised producer cursor
        self._wseq = seq
        return True

    def try_get(self) -> Optional[bytes]:
        """Pop the next message, or None when the ring is empty.
        Consumer side only."""
        u32, buf = self._U32, self._buf
        r = self._rseq
        off = self.HEADER + (r % self.nslots) * self.slot_bytes
        expect = (r + 1) & 0xFFFFFFFF
        if u32.unpack_from(buf, off)[0] != expect:
            return None
        n = u32.unpack_from(buf, off + 4)[0]
        data = bytes(buf[off + 8:off + 8 + n])
        self._rseq = expect
        u32.pack_into(buf, 64, expect)     # release the slot
        return data

    def drain(self) -> List[bytes]:
        out: List[bytes] = []
        msg = self.try_get()
        while msg is not None:
            out.append(msg)
            msg = self.try_get()
        return out

    def close(self) -> None:
        try:
            self._buf.release()
        except Exception:
            pass


class _Alloc:
    __slots__ = ("offset", "nbytes", "sealed", "accessed", "spilling")

    def __init__(self, offset: int, nbytes: int):
        self.offset = offset
        self.nbytes = nbytes
        self.sealed = False
        # a located/read object may be backing live zero-copy views in
        # some process; evicting its arena region would reuse the bytes
        # under those views. Never-accessed objects are safe to spill.
        self.accessed = False
        self.spilling = False  # selected for spill; write in progress


class ShmObjectStore:
    """Owner-side object table over a ShmArena: create/seal/locate/free,
    with a DISK SPILL tier under memory pressure.

    Reference: plasma's ObjectLifecycleManager (create->seal lifecycle)
    + the raylet's LocalObjectManager (spill primary copies to external
    storage when the store fills, restore on demand, delete spilled
    files when refs die — ray: src/ray/raylet/local_object_manager.cc).
    Eviction policy: FIFO over sealed objects that were never located/
    read (zero-copy safety, see _Alloc.accessed); the incoming object
    itself spills when eviction can't free enough.
    """

    def __init__(self, capacity_bytes: int,
                 spill_dir: Optional[str] = None):
        from ray_tpu._private.config import GLOBAL_CONFIG

        self.arena = ShmArena(capacity_bytes)
        self._capacity = capacity_bytes
        # spill hysteresis: once the arena is forced to spill, keep
        # evicting until usage drops back under this fraction of
        # capacity so the very next create doesn't spill again.
        # >= 1.0 means purely reactive (free only what the allocation
        # needs)
        self._spill_threshold = float(
            getattr(GLOBAL_CONFIG, "object_spill_threshold", 1.0))
        self._table: Dict[ObjectID, _Alloc] = {}
        self._spilled: Dict[ObjectID, Tuple[str, int]] = {}
        configured = getattr(GLOBAL_CONFIG, "object_spill_dir", "")
        self._spill_dir = (spill_dir or configured
                           or tempfile.mkdtemp(prefix="ray_tpu_spill_"))
        os.makedirs(self._spill_dir, exist_ok=True)
        self.num_spilled = 0
        self.num_restored = 0
        # zero-copy pins: object_id -> live-view count; freed-while-
        # pinned ranges wait in _deferred until their last unpin
        self._pins: Dict[ObjectID, int] = {}
        self._deferred: Dict[ObjectID, _Alloc] = {}
        self._lock = runtime_sanitizer.wrap_lock(
            threading.Lock(),
            "_private.runtime.shm_store.ShmObjectStore._lock")

    # -- create/seal lifecycle --------------------------------------------
    def create(self, object_id: ObjectID, nbytes: int) -> int:
        try:
            offset = self.arena.allocate(nbytes)
        except ObjectStoreFullError:
            self._spill_for(nbytes)
            offset = self.arena.allocate(nbytes)  # may raise again
        with self._lock:
            if object_id in self._table or object_id in self._spilled:
                self.arena.free(offset, nbytes)
                raise ValueError(f"object {object_id.hex()} already created")
            self._table[object_id] = _Alloc(offset, nbytes)
        runtime_sanitizer.ledger_alloc("arena", object_id, nbytes)
        return offset

    def seal(self, object_id: ObjectID) -> None:
        with self._lock:
            self._table[object_id].sealed = True

    def locate(self, object_id: ObjectID) -> Optional[Tuple[int, int]]:
        """(offset, nbytes) of a SEALED arena-resident object, else None
        (spilled objects read through get_serialized)."""
        with self._lock:
            alloc = self._table.get(object_id)
            if alloc is None or not alloc.sealed:
                return None
            alloc.accessed = True
            return alloc.offset, alloc.nbytes

    # -- spilling ----------------------------------------------------------
    def _spill_path(self, object_id: ObjectID) -> str:
        return os.path.join(self._spill_dir, object_id.hex())

    def _spill_for(self, nbytes: int) -> None:
        """Evict sealed never-accessed objects (FIFO) to disk until
        ~nbytes could fit. Best effort: stops when nothing is safely
        evictable.

        The victim STAYS resident (flagged ``spilling``) until its file
        write commits, so concurrent readers never observe a window
        where the object is in neither table; the commit re-checks that
        the object wasn't freed or accessed while the write ran."""
        # object_spill_threshold adds hysteresis: a forced spill frees
        # down to that fraction of capacity (not just the bytes this
        # allocation needs), so a store hovering at the rim doesn't
        # re-spill on every create; >= 1.0 is purely reactive
        target = max(nbytes,
                     nbytes + int(self._capacity
                                  * (1.0 - self._spill_threshold)))
        while self.arena.free_bytes() < target:
            with self._lock:
                victim = next(
                    (oid for oid, a in self._table.items()
                     if a.sealed and not a.accessed and not a.spilling),
                    None)
                if victim is None:
                    return
                alloc = self._table[victim]
                alloc.spilling = True
            path = self._spill_path(victim)
            tmp = f"{path}.{os.getpid()}.tmp"
            try:
                data = bytes(self.arena.view(alloc.offset, alloc.nbytes))
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
            except OSError:
                # disk failure: the object simply stays resident
                with self._lock:
                    alloc.spilling = False
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return
            with self._lock:
                current = self._table.get(victim)
                if current is not alloc or alloc.accessed:
                    # freed or read mid-write: abandon the spill (a
                    # reader may hold zero-copy views of the region)
                    if current is alloc:
                        alloc.spilling = False
                    committed = False
                else:
                    del self._table[victim]
                    self._spilled[victim] = (path, alloc.nbytes)
                    self.num_spilled += 1
                    committed = True
            if committed:
                self.arena.free(alloc.offset, alloc.nbytes)
            else:
                try:
                    os.unlink(path)
                except OSError:
                    pass


    def contains(self, object_id: ObjectID) -> bool:
        if self.locate(object_id) is not None:
            return True
        with self._lock:
            return object_id in self._spilled

    # -- owner-process direct IO ------------------------------------------
    def put_serialized(self, object_id: ObjectID,
                       sobj: SerializedObject) -> Tuple[int, int]:
        """create + write + seal in the owner process (driver puts); an
        arena that stays full even after eviction spills the NEW object
        straight to disk."""
        nbytes = sobj.framed_nbytes()
        try:
            offset = self.create(object_id, nbytes)
        except ObjectStoreFullError:
            path = self._spill_path(object_id)
            tmp = f"{path}.{os.getpid()}.tmp"
            buf = bytearray(nbytes)
            sobj.write_into(memoryview(buf))
            with open(tmp, "wb") as f:
                f.write(buf)
            os.replace(tmp, path)
            with self._lock:
                self._spilled[object_id] = (path, nbytes)
                self.num_spilled += 1
            runtime_sanitizer.ledger_alloc("spill", object_id, nbytes)
            return (-1, nbytes)
        sobj.write_into(self.arena.view(offset, nbytes))
        self.seal(object_id)
        return offset, nbytes

    # -- peer transfer plane (chunked) ------------------------------------
    def acquire_raw(self, object_id: ObjectID) -> Optional[memoryview]:
        """Pinned raw framed-bytes view of a sealed arena-resident
        object, for zero-copy chunked sends; None when spilled/absent.
        The caller MUST release_raw() when done or the range never
        frees."""
        with self._lock:
            alloc = self._table.get(object_id)
            if alloc is None or not alloc.sealed:
                return None
            alloc.accessed = True
            self._pins[object_id] = self._pins.get(object_id, 0) + 1
            return self.arena.view(alloc.offset, alloc.nbytes)

    def release_raw(self, object_id: ObjectID) -> None:
        self.unpin(object_id)

    def spilled_path(self, object_id: ObjectID) -> Optional[Tuple[str, int]]:
        """(path, nbytes) of a spilled object's on-disk framed bytes."""
        with self._lock:
            return self._spilled.get(object_id)

    def begin_adopt(self, object_id: ObjectID, nbytes: int):
        """Start adopting an incoming peer object of `nbytes` framed
        bytes WITHOUT ever holding them all in anonymous memory:
        ("arena", view) when it fits (the caller fills the view chunk
        by chunk), else ("spill", file) streaming straight to the spill
        tier — how a >arena-sized object lands without OOM. Finish
        with finish_adopt / abort_adopt."""
        try:
            offset = self.create(object_id, nbytes)
            return ("arena", self.arena.view(offset, nbytes))
        except ObjectStoreFullError:
            path = self._spill_path(object_id)
            return ("spill", open(f"{path}.{os.getpid()}.adopt", "wb"))

    def finish_adopt(self, object_id: ObjectID, nbytes: int, kind: str,
                     f=None) -> None:
        if kind == "arena":
            self.seal(object_id)
            return
        f.close()
        path = self._spill_path(object_id)
        os.replace(f"{path}.{os.getpid()}.adopt", path)
        with self._lock:
            self._spilled[object_id] = (path, nbytes)
            self.num_spilled += 1
        runtime_sanitizer.ledger_alloc("spill", object_id, nbytes)

    def abort_adopt(self, object_id: ObjectID, kind: str, f=None) -> None:
        if kind == "arena":
            with self._lock:
                alloc = self._table.pop(object_id, None)
            if alloc is not None:
                self.arena.free(alloc.offset, alloc.nbytes)
            runtime_sanitizer.ledger_free(object_id)
            return
        try:
            f.close()
            os.unlink(f"{self._spill_path(object_id)}.{os.getpid()}.adopt")
        except OSError:
            pass

    def get_serialized_for_view(
            self, object_id: ObjectID
    ) -> Tuple[Optional[SerializedObject], bool]:
        """(sobj, pinned) for a caller that will hand out ZERO-COPY
        views. pinned=True only when served straight from the arena —
        the range is then atomically pinned against free_object reuse
        and the caller must unpin() once the views are collected (the
        plasma Release analog; without it, freeing a consumed block
        while an Arrow/numpy view is alive hands its bytes to the next
        allocation and the view silently mutates). Spill-tier reads
        copy off disk and need no pin."""
        with self._lock:
            alloc = self._table.get(object_id)
            if alloc is not None and alloc.sealed:
                alloc.accessed = True
                self._pins[object_id] = self._pins.get(object_id, 0) + 1
                loc = (alloc.offset, alloc.nbytes)
            else:
                loc = None
        if loc is not None:
            return SerializedObject.from_bytes(self.arena.view(*loc)), True
        return self.get_serialized(object_id), False

    def get_serialized(self, object_id: ObjectID
                       ) -> Optional[SerializedObject]:
        loc = self.locate(object_id)
        if loc is not None:
            offset, nbytes = loc
            return SerializedObject.from_bytes(
                self.arena.view(offset, nbytes))
        with self._lock:
            spilled = self._spilled.get(object_id)
        if spilled is None:
            return None
        # restore from disk (reference: spilled-object restore path); a
        # concurrent free may have unlinked the file -> treat as gone
        try:
            with open(spilled[0], "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return None
        with self._lock:
            self.num_restored += 1
        return SerializedObject.from_bytes(data)

    def unpin(self, object_id: ObjectID) -> None:
        """Zero-copy views of the object were collected; recycle a
        deferred range once the last pin drops."""
        deferred = None
        with self._lock:
            count = self._pins.get(object_id, 0) - 1
            if count > 0:
                self._pins[object_id] = count
            else:
                self._pins.pop(object_id, None)
                deferred = self._deferred.pop(object_id, None)
        if deferred is not None:
            self.arena.free(deferred.offset, deferred.nbytes)

    def free_object(self, object_id: ObjectID) -> None:
        runtime_sanitizer.ledger_free(object_id)
        with self._lock:
            alloc = self._table.pop(object_id, None)
            spilled = self._spilled.pop(object_id, None)
            if alloc is not None and self._pins.get(object_id):
                # live zero-copy views: quarantine the range until the
                # last pin drops (unpin) instead of handing the bytes
                # to the next allocation under those views
                self._deferred[object_id] = alloc
                alloc = None
        if alloc is not None:
            self.arena.free(alloc.offset, alloc.nbytes)
        if spilled is not None:
            try:
                os.unlink(spilled[0])  # spilled files die with the ref
            except FileNotFoundError:
                pass

    # -- stats / lifecycle -------------------------------------------------
    def num_objects(self) -> int:
        with self._lock:
            return len(self._table) + len(self._spilled)

    def num_spilled_objects(self) -> int:
        with self._lock:
            return len(self._spilled)

    def spilled_bytes(self) -> int:
        with self._lock:
            return sum(n for _, n in self._spilled.values())

    def used_bytes(self) -> int:
        return self.arena.size - self.arena.free_bytes()

    def shutdown(self) -> None:
        self.arena.close()
        self.arena.unlink()
        with self._lock:
            self._spilled.clear()
        shutil.rmtree(self._spill_dir, ignore_errors=True)
